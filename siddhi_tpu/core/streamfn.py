"""Stream functions: 1->N in-chain transforms appending attributes
(reference: CORE/query/processor/stream/function/StreamFunctionProcessor.java,
LogStreamProcessor.java:330, Pol2CartStreamFunctionProcessor.java:185).

TPU-native design: a stream function contributes (new_names, new_types,
fn(env) -> new column block) compiled into the query's fused step — the
reference's per-event process(...) object becomes column math.  `log` uses
`jax.debug.callback`, the XLA-native host tap, instead of breaking the
fusion.
"""
from __future__ import annotations

import logging
from typing import Dict

import jax
import jax.numpy as jnp

from ..query_api.expression import Constant
from .executor import CompileError, Scope, compile_expression

log = logging.getLogger("siddhi_tpu")


class StreamFunctionDef:
    """SPI: compile(params, scope, sid) ->
    (new_names, new_types, fn(env, valid) -> (new_cols tuple, keep_mask)).

    `sid` is the input stream id (a string).  `env` maps stream id -> column
    tuple plus "__ts__"/"__now__"/"__kind__" arrays.  Per-extension config is
    available as scope.config_manager (utils/config.py) when the app was
    created with one.
    """

    def compile(self, params, scope: Scope, sid: str):
        raise NotImplementedError


class LogStreamFunction(StreamFunctionDef):
    """`#log([priority,] message)` — passes events through, emitting the
    message + batch size on the host via jax.debug.callback."""

    def compile(self, params, scope, sid):
        message = "events"
        priority = "INFO"
        if any(not isinstance(p, Constant) for p in params):
            raise CompileError(
                "log(...) parameters must be constants (per-event message "
                "expressions are not supported on the fused device path)")
        if len(params) == 1:
            message = str(params[0].value)
        elif len(params) >= 2:
            priority = str(params[0].value).upper()
            message = str(params[1].value)
        level = getattr(logging, priority, logging.INFO)

        def host_log(n):
            if int(n):  # timer ticks / all-padding batches stay silent
                log.log(level, "%s : %d event(s)", message, int(n))

        def fn(env, valid):
            import jax.numpy as _jnp
            from . import event as _ev
            arriving = valid
            if "__kind__" in env:  # count CURRENT rows only, not EXPIRED
                arriving = _jnp.logical_and(valid,
                                            env["__kind__"] == _ev.CURRENT)
            jax.debug.callback(host_log, jnp.sum(arriving.astype(jnp.int32)))
            return (), valid

        return [], [], fn


class Pol2CartStreamFunction(StreamFunctionDef):
    """`#pol2Cart(theta, rho[, z])` appends cartesian x, y (reference:
    Pol2CartStreamFunctionProcessor)."""

    def compile(self, params, scope, sid):
        if len(params) not in (2, 3):
            raise CompileError("pol2Cart(theta, rho[, z]) takes 2-3 args")
        theta = compile_expression(params[0], scope)
        rho = compile_expression(params[1], scope)
        zc = compile_expression(params[2], scope) if len(params) == 3 else None

        def fn(env, valid):
            t = jnp.asarray(theta.fn(env), jnp.float64)
            r = jnp.asarray(rho.fn(env), jnp.float64)
            out = (r * jnp.cos(t), r * jnp.sin(t))
            if zc is not None:  # cylindrical: z passes through alongside x, y
                out = out + (jnp.asarray(zc.fn(env), jnp.float64),)
            return out, valid

        names = ["x", "y"] + (["z"] if zc is not None else [])
        return names, ["DOUBLE"] * len(names), fn


STREAM_FUNCTIONS: Dict[str, StreamFunctionDef] = {
    "log": LogStreamFunction(),
    "pol2Cart": Pol2CartStreamFunction(),
}


def stream_function_extension(name: str):
    """Decorator registering a custom stream function
    (reference: @Extension stream function types)."""
    def deco(cls):
        STREAM_FUNCTIONS[name] = cls() if isinstance(cls, type) else cls
        return cls
    return deco
