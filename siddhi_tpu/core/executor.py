"""Expression compiler: SiddhiQL expression AST -> JAX column ops.

This replaces the reference's interpreter-object executor trees
(CORE/executor/ExpressionExecutor.java:27, the ~106 generated-style compare
classes under CORE/executor/condition/compare/*, math executors under
CORE/executor/math/*, and the giant type-dispatch in
CORE/util/parser/ExpressionParser.java:224).  Instead of one Java object per
AST node executing per event, we compile each expression once into a function
over columnar environments; XLA fuses the result into the surrounding query
step.  Filters become boolean masks, not control flow.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp

from ..query_api.expression import (
    Add,
    And,
    AttributeFunction,
    Compare,
    Constant,
    Divide,
    Expression,
    In,
    IsNull,
    Mod,
    Multiply,
    Not,
    Or,
    Subtract,
    Variable,
)
from . import event as ev

# numeric promotion order (reference: ExpressionParser type dispatch)
_NUMERIC_ORDER = {"INT": 0, "LONG": 1, "FLOAT": 2, "DOUBLE": 3}
NUMERIC_TYPES = set(_NUMERIC_ORDER)

AGGREGATOR_NAMES = {
    "sum", "avg", "count", "min", "max", "distinctCount", "stdDev",
    "minForever", "maxForever", "and", "or", "unionSet",
}


from ..exceptions import CompileError  # noqa: E402  (canonical home)


def promote(t1: str, t2: str) -> str:
    if t1 not in _NUMERIC_ORDER or t2 not in _NUMERIC_ORDER:
        raise CompileError(f"cannot apply arithmetic to {t1}/{t2}")
    return max(t1, t2, key=lambda t: _NUMERIC_ORDER[t])


@dataclasses.dataclass
class CompiledExpr:
    """fn(env) -> array; env is a dict scope_key -> tuple-of-column-arrays,
    plus '__ts__:<key>' timestamp arrays and '__now__' scalar."""

    fn: Callable[[Dict[str, Any]], Any]
    type: str                      # result attribute type
    is_constant: bool = False
    constant_value: Any = None


class Scope:
    """Resolves Variable nodes to (scope_key, column_position, type).

    scope keys: for single input streams there is one key (the stream id, and
    its reference id if aliased).  Joins register both sides; patterns register
    e1/e2/... reference ids.  `None`-qualified variables resolve through
    `default_keys` in order (ambiguity is an error, as in the reference).
    """

    # per-extension config access (utils/config.py); set by the planner when
    # the app runtime carries a ConfigManager
    config_manager = None
    # `define function` script definitions (id -> FunctionDefinition); set by
    # the planner from the app
    script_functions = None
    # set True when a UUID() call compiles through this scope; planners copy
    # it onto the planned query so emission materializes sentinels exactly once
    uses_uuid = False

    def __init__(self):
        self._sources: Dict[str, "ev.Schema"] = {}
        self._aliases: Dict[str, str] = {}
        self.default_keys: List[str] = []
        # pseudo-columns bound by the selector (aggregator outputs, projections)
        self._bound: Dict[str, CompiledExpr] = {}

    def add_source(self, key: str, schema: "ev.Schema",
                   alias: Optional[str] = None, default: bool = True) -> None:
        self._sources[key] = schema
        if alias and alias != key:
            self._aliases[alias] = key
        if default:
            self.default_keys.append(key)

    def bind(self, name: str, compiled: CompiledExpr) -> None:
        self._bound[name] = compiled

    @property
    def bound_names(self):
        return self._bound

    def schema(self, key: str) -> "ev.Schema":
        key = self._aliases.get(key, key)
        return self._sources[key]

    def has_source(self, key: str) -> bool:
        return key in self._sources or key in self._aliases

    def resolve(self, var: Variable) -> Tuple[Optional[str], int, str]:
        if var.stream_id is not None:
            key = self._aliases.get(var.stream_id, var.stream_id)
            if key not in self._sources:
                raise CompileError(
                    f"unknown stream reference {var.stream_id!r} for attribute "
                    f"{var.attribute_name!r}")
            schema = self._sources[key]
            pos = schema.position(var.attribute_name)
            return key, pos, schema.types[pos]
        if var.attribute_name in self._bound:
            return None, -1, self._bound[var.attribute_name].type
        hits = []
        for key in self.default_keys:
            schema = self._sources[key]
            if var.attribute_name in schema.names:
                hits.append((key, schema))
        if not hits:
            raise CompileError(f"unknown attribute {var.attribute_name!r}")
        if len(set(k for k, _ in hits)) > 1:
            raise CompileError(
                f"ambiguous attribute {var.attribute_name!r} (in "
                f"{[k for k, _ in hits]})")
        key, schema = hits[0]
        pos = schema.position(var.attribute_name)
        return key, pos, schema.types[pos]


def _cast_to(x, t: str):
    return x.astype(ev.dtype_of(t)) if hasattr(x, "astype") else jnp.asarray(
        x, ev.dtype_of(t))


# -- numeric null support (in-band reserved values, core/event.py) -----------
# The reference's executors pass boxed Java nulls through every operator:
# arithmetic on null yields null, comparisons with null yield false
# (CORE/executor/condition/compare/*, math/*).  Columnar equivalents below:
# null detection is one fused compare per nullable operand; constants are
# statically never null so filters on constants pay one extra AND at most.

def _maybe_null(c: CompiledExpr) -> bool:
    """Can this expression's column contain the reserved null value?"""
    return not c.is_constant and c.type in (
        "INT", "LONG", "FLOAT", "DOUBLE", "STRING", "OBJECT")


def _null_of(c: CompiledExpr, val):
    """Null mask of an operand's ORIGINAL (pre-promotion) value."""
    return ev.null_mask(val, c.type)


def _null_cast(x, from_t: str, to_t: str):
    """astype that maps from_t's null representation onto to_t's (an int
    sentinel cast to float must become NaN, not -9.2e18)."""
    d = ev.dtype_of(to_t)
    out = jnp.asarray(x).astype(d)
    if from_t == to_t or from_t not in NUMERIC_TYPES or \
            to_t not in NUMERIC_TYPES:
        return out
    return jnp.where(ev.null_mask(x, from_t),
                     jnp.asarray(ev.null_value(to_t), d), out)


def compile_expression(expr: Expression, scope: Scope) -> CompiledExpr:
    """Recursively compile an expression tree to a column function."""
    if isinstance(expr, Constant):
        dtype = ev.dtype_of(expr.type)
        if expr.type == "STRING":
            # interned eagerly at compile time against the app interner so the
            # id is a trace-time constant
            interner = getattr(scope, "interner", None)
            if interner is None:
                raise CompileError("scope has no interner for string constant")
            sid = jnp.asarray(interner.intern(expr.value), jnp.int32)
            return CompiledExpr(lambda env, _v=sid: _v, "STRING", True,
                                expr.value)
        val = jnp.asarray(expr.value, dtype)
        return CompiledExpr(lambda env, _v=val: _v, expr.type, True, expr.value)

    if isinstance(expr, Variable):
        key, pos, t = scope.resolve(expr)
        if key is None:  # bound pseudo-column (aggregator output etc.)
            inner = scope.bound_names[expr.attribute_name]
            return CompiledExpr(inner.fn, inner.type)
        if expr.stream_index is not None:
            # pattern count-state index: e1[2].attr / e1[last].attr resolve
            # through per-depth env entries provided by the pattern runtime
            idx = expr.stream_index if expr.stream_index >= 0 else -1
            def fn(env, _k=f"{key}@{idx}", _p=pos):
                return env[_k][_p]
            return CompiledExpr(fn, t)
        def fn(env, _k=key, _p=pos):
            return env[_k][_p]
        return CompiledExpr(fn, t)

    if isinstance(expr, (Add, Subtract, Multiply, Divide, Mod)):
        l = compile_expression(expr.left, scope)
        r = compile_expression(expr.right, scope)
        t = promote(l.type, r.type)
        dtype = ev.dtype_of(t)
        # null in → null out (reference: math executors return null on null)
        null_check = _maybe_null(l) or _maybe_null(r)
        nv = jnp.asarray(ev.null_value(t), dtype)

        def _nullify(out, a, b, _l=l, _r=r, _nv=nv):
            n = None
            if _maybe_null(_l):
                n = _null_of(_l, a)
            if _maybe_null(_r):
                rn = _null_of(_r, b)
                n = rn if n is None else jnp.logical_or(n, rn)
            return jnp.where(n, _nv, out) if n is not None else out

        op = {
            Add: jnp.add, Subtract: jnp.subtract, Multiply: jnp.multiply,
            Mod: jnp.mod,
        }.get(type(expr))
        if op is not None:
            def fn(env, _l=l.fn, _r=r.fn, _op=op, _d=dtype):
                a, b = _l(env), _r(env)
                out = _op(jnp.asarray(a).astype(_d),
                          jnp.asarray(b).astype(_d))
                return _nullify(out, a, b) if null_check else out
            return CompiledExpr(fn, t)
        # divide: integer types use truncating division toward zero (Java /)
        if t in ("INT", "LONG"):
            def fn(env, _l=l.fn, _r=r.fn, _d=dtype):
                a0, b0 = _l(env), _r(env)
                a = jnp.asarray(a0).astype(_d)
                b = jnp.asarray(b0).astype(_d)
                q = jnp.where(b == 0, jnp.zeros_like(a), a)  # guard div0
                b = jnp.where(b == 0, jnp.ones_like(b), b)
                out = (jnp.sign(q) * jnp.sign(b) *
                       (jnp.abs(q) // jnp.abs(b))).astype(_d)
                return _nullify(out, a0, b0) if null_check else out
        else:
            def fn(env, _l=l.fn, _r=r.fn, _d=dtype):
                a0, b0 = _l(env), _r(env)
                out = jnp.asarray(a0).astype(_d) / jnp.asarray(b0).astype(_d)
                return _nullify(out, a0, b0) if null_check else out
        return CompiledExpr(fn, t)

    if isinstance(expr, Compare):
        l = compile_expression(expr.left, scope)
        r = compile_expression(expr.right, scope)
        if l.type == "STRING" and r.type == "STRING":
            if expr.operator not in ("==", "!="):
                raise CompileError(
                    "string ordering comparisons are not supported on device")
        elif l.type == "BOOL" or r.type == "BOOL":
            pass
        else:
            t = promote(l.type, r.type)
        opf = {
            "<": jnp.less, "<=": jnp.less_equal, ">": jnp.greater,
            ">=": jnp.greater_equal, "==": jnp.equal, "!=": jnp.not_equal,
        }[expr.operator]
        # comparisons with null are FALSE (reference: every compare executor
        # null-checks first, including null == null and null != x)
        null_check = _maybe_null(l) or _maybe_null(r)

        def fn(env, _l=l.fn, _r=r.fn, _op=opf):
            a, b = _l(env), _r(env)
            out = _op(a, b)
            if null_check:
                if _maybe_null(l):
                    out = jnp.logical_and(out,
                                          jnp.logical_not(_null_of(l, a)))
                if _maybe_null(r):
                    out = jnp.logical_and(out,
                                          jnp.logical_not(_null_of(r, b)))
            return out
        return CompiledExpr(fn, "BOOL")

    if isinstance(expr, And):
        l = compile_expression(expr.left, scope)
        r = compile_expression(expr.right, scope)
        return CompiledExpr(
            lambda env, _l=l.fn, _r=r.fn: jnp.logical_and(_l(env), _r(env)),
            "BOOL")

    if isinstance(expr, Or):
        l = compile_expression(expr.left, scope)
        r = compile_expression(expr.right, scope)
        return CompiledExpr(
            lambda env, _l=l.fn, _r=r.fn: jnp.logical_or(_l(env), _r(env)),
            "BOOL")

    if isinstance(expr, Not):
        inner = compile_expression(expr.expression, scope)
        return CompiledExpr(
            lambda env, _i=inner.fn: jnp.logical_not(_i(env)), "BOOL")

    if isinstance(expr, IsNull):
        if expr.expression is None:
            # isNull(stream) in patterns — handled by the pattern runtime
            raise CompileError("stream-level is null only valid inside patterns")
        inner = compile_expression(expr.expression, scope)
        if _maybe_null(inner):
            return CompiledExpr(
                lambda env, _i=inner.fn, _t=inner.type:
                ev.null_mask(_i(env), _t), "BOOL")
        return CompiledExpr(
            lambda env, _i=inner.fn: jnp.zeros(jnp.shape(_i(env)), jnp.bool_),
            "BOOL")

    if isinstance(expr, In):
        inner = compile_expression(expr.expression, scope)
        def fn(env, _i=inner.fn, _src=expr.source_id):
            probe = env["__in__:" + _src]
            return probe(_i(env))
        return CompiledExpr(fn, "BOOL")

    if isinstance(expr, AttributeFunction):
        return _compile_function(expr, scope)

    raise CompileError(f"cannot compile expression node {type(expr).__name__}")


# ---------------------------------------------------------------------------
# Built-in scalar functions
# (reference: CORE/executor/function/* — cast/convert/coalesce/ifThenElse/
#  instanceOf*/maximum/minimum/default/eventTimestamp/currentTimeMillis/UUID)
# ---------------------------------------------------------------------------

def _compile_function(expr: AttributeFunction, scope: Scope) -> CompiledExpr:
    name = expr.name
    full = f"{expr.namespace}:{name}" if expr.namespace else name
    if not expr.namespace and scope.script_functions and \
            name in scope.script_functions:
        return _compile_script_function(scope.script_functions[name],
                                        expr, scope)
    args = expr.parameters

    if name in AGGREGATOR_NAMES and not expr.namespace:
        raise CompileError(
            f"aggregator {name!r} outside a select clause is not valid")
    from .extension import attribute_aggregator_registry
    if full in attribute_aggregator_registry():
        raise CompileError(
            f"aggregator {full!r} outside a select clause is not valid")

    def carg(i):
        return compile_expression(args[i], scope)

    if full in ("cast", "convert"):
        src = carg(0)
        if not isinstance(args[1], Constant):
            raise CompileError(f"{full}() target type must be a constant")
        target = str(args[1].value).upper()
        target = {"STRING": "STRING", "INT": "INT", "INTEGER": "INT",
                  "LONG": "LONG", "FLOAT": "FLOAT", "DOUBLE": "DOUBLE",
                  "BOOL": "BOOL", "BOOLEAN": "BOOL"}[target]
        if target == "STRING" or src.type == "STRING":
            if target == src.type:
                return src
            raise CompileError("string<->numeric cast requires host fallback")
        return CompiledExpr(
            lambda env, _s=src, _t=target: _null_cast(_s.fn(env), _s.type, _t),
            target)

    if full == "coalesce":
        compiled = [carg(i) for i in range(len(args))]
        t = compiled[0].type
        if t in ("STRING", "OBJECT"):
            def fn(env, _c=compiled):
                out = _c[0].fn(env)
                for c in _c[1:]:
                    out = jnp.where(out == ev.NULL_ID, c.fn(env), out)
                return out
            return CompiledExpr(fn, t)
        for c in compiled[1:]:
            t = promote(t, c.type)

        def fn(env, _c=compiled, _t=t):
            out = _null_cast(_c[0].fn(env), _c[0].type, _t)
            for c in _c[1:]:
                out = jnp.where(ev.null_mask(out, _t),
                                _null_cast(c.fn(env), c.type, _t), out)
            return out
        return CompiledExpr(fn, t)

    if full == "ifThenElse":
        cond, then, els = carg(0), carg(1), carg(2)
        t = then.type if then.type == els.type else promote(then.type, els.type)
        def fn(env, _c=cond.fn, _t=then, _e=els, _ty=t):
            return jnp.where(_c(env), _null_cast(_t.fn(env), _t.type, _ty),
                             _null_cast(_e.fn(env), _e.type, _ty))
        return CompiledExpr(fn, t)

    if full in ("maximum", "minimum"):
        compiled = [carg(i) for i in range(len(args))]
        t = compiled[0].type
        for c in compiled[1:]:
            t = promote(t, c.type)
        d = ev.dtype_of(t)
        red = jnp.maximum if full == "maximum" else jnp.minimum
        # nulls are SKIPPED, all-null returns null (reference:
        # MaximumFunctionExecutor ignores null arguments)
        ident = jnp.asarray(
            (-jnp.inf if full == "maximum" else jnp.inf)
            if d in (jnp.float32, jnp.float64)
            else (jnp.iinfo(d).min + 1 if full == "maximum"
                  else jnp.iinfo(d).max), d)

        def fn(env, _c=compiled, _d=d, _r=red, _t=t, _id=ident):
            out = None
            allnull = None
            for c in _c:
                v = _null_cast(c.fn(env), c.type, _t)
                n = ev.null_mask(v, _t)
                lifted = jnp.where(n, _id, v)
                out = lifted if out is None else _r(out, lifted)
                allnull = n if allnull is None else jnp.logical_and(allnull, n)
            return jnp.where(allnull, jnp.asarray(ev.null_value(_t), _d), out)
        return CompiledExpr(fn, t)

    if full == "createSet":
        raise CompileError(
            "createSet is only valid inside unionSet(createSet(attr))")

    if full == "sizeOfSet":
        src = carg(0)
        if src.type != "SET":
            raise CompileError(
                "sizeOfSet expects a set value "
                "(e.g. sizeOfSet(unionSet(createSet(attr))))")
        # the SET pseudo-value IS the running distinct count
        return CompiledExpr(src.fn, "LONG")

    if full == "UUID":
        # one unique id per output event (reference: CORE/executor/function/
        # UUIDFunctionExecutor).  Device-side the column is the sentinel;
        # materialization to real interned ids happens once at the emission/
        # storage boundary (planners read this flag) — strings never ride
        # the device
        scope.uses_uuid = True

        def fn(env):
            return jnp.full(jnp.shape(env["__ts__"]), ev.UUID_SENTINEL,
                            ev.dtype_of("STRING"))
        return CompiledExpr(fn, "STRING")

    if full == "eventTimestamp":
        def fn(env):
            return env["__ts__"]
        return CompiledExpr(fn, "LONG")

    if full == "currentTimeMillis":
        def fn(env):
            # __now__ is a scalar; projections must be [B] columns
            return jnp.broadcast_to(jnp.asarray(env["__now__"], jnp.int64),
                                    jnp.shape(env["__ts__"]))
        return CompiledExpr(fn, "LONG")

    if full.startswith("instanceOf"):
        target = {"instanceOfBoolean": "BOOL", "instanceOfString": "STRING",
                  "instanceOfInteger": "INT", "instanceOfLong": "LONG",
                  "instanceOfFloat": "FLOAT", "instanceOfDouble": "DOUBLE"}[full]
        src = carg(0)
        hit = src.type == target
        def fn(env, _s=src.fn, _h=hit):
            return jnp.full(jnp.shape(_s(env)), _h, jnp.bool_)
        return CompiledExpr(fn, "BOOL")

    if full == "default":
        src, dflt = carg(0), carg(1)
        if src.type in ("STRING", "OBJECT"):
            def fn(env, _s=src.fn, _d=dflt.fn):
                v = _s(env)
                return jnp.where(v == ev.NULL_ID, _d(env), v)
            return CompiledExpr(fn, src.type)

        def fn(env, _s=src, _d=dflt):
            v = _s.fn(env)
            return jnp.where(ev.null_mask(v, _s.type),
                             _null_cast(_d.fn(env), _d.type, _s.type), v)
        return CompiledExpr(fn, src.type)

    # math extension namespace (device-friendly subset)
    _MATH = {
        "math:abs": (jnp.abs, None), "math:ceil": (jnp.ceil, "DOUBLE"),
        "math:floor": (jnp.floor, "DOUBLE"), "math:sqrt": (jnp.sqrt, "DOUBLE"),
        "math:exp": (jnp.exp, "DOUBLE"), "math:ln": (jnp.log, "DOUBLE"),
        "math:log10": (jnp.log10, "DOUBLE"), "math:sin": (jnp.sin, "DOUBLE"),
        "math:cos": (jnp.cos, "DOUBLE"), "math:tan": (jnp.tan, "DOUBLE"),
        "math:round": (jnp.round, None),
    }
    if full in _MATH:
        f, rt = _MATH[full]
        src = carg(0)
        t = rt or src.type
        d = ev.dtype_of(t)
        return CompiledExpr(
            lambda env, _s=src.fn, _f=f, _d=d: _f(_s(env)).astype(_d), t)
    if full == "math:power":
        a, b = carg(0), carg(1)
        return CompiledExpr(
            lambda env, _a=a.fn, _b=b.fn: jnp.power(
                jnp.asarray(_a(env), jnp.float32),
                jnp.asarray(_b(env), jnp.float32)), "DOUBLE")

    # user-registered scalar extensions
    reg = _extension_registry()
    if full in reg:
        impl = reg[full]
        compiled = [carg(i) for i in range(len(args))]
        return impl(compiled)

    raise CompileError(f"unknown function {full!r}")


def _extension_registry():
    from .extension import scalar_function_registry
    return scalar_function_registry()


def _build_script_callable(fd):
    """Compile a `define function` body into a host callable
    fn(data: list) -> value through the registered script engine for the
    definition's language (reference: Script extensions resolved via
    ScriptExtensionHolder; python ships built in, others plug in with
    @script_engine('<lang>'))."""
    from .extension import script_engine_registry
    lang = (fd.language or "").lower()
    engine = script_engine_registry().get(lang)
    if engine is None:
        known = sorted(script_engine_registry())
        raise CompileError(
            f"script language {fd.language!r} is not available in this "
            f"runtime (registered engines: {known}); define function "
            f"{fd.id}[python] ... or register a @script_engine")
    return engine(fd)


def _python_script_engine(fd):
    """Built-in python script engine: the body sees its arguments as the
    `data` list and returns the result (the reference's javascript scripts
    follow the same convention)."""
    import textwrap
    body = textwrap.dedent(fd.body).strip("\n")
    ns: Dict[str, Any] = {"np": __import__("numpy"),
                          "math": __import__("math")}
    if "return" not in body and "\n" not in body:
        src = f"def __scriptfn__(data):\n    return ({body})"
    else:
        src = "def __scriptfn__(data):\n" + textwrap.indent(body, "    ")
    try:
        exec(src, ns)  # noqa: S102 — user-defined script function body
    except SyntaxError as e:
        raise CompileError(
            f"invalid python body in define function {fd.id!r}: {e}")
    return ns["__scriptfn__"]


def _compile_script_function(fd, expr: AttributeFunction,
                             scope: Scope) -> CompiledExpr:
    """Script functions run on the host via jax.pure_callback, one batched
    call per step (the reference evaluates its JS/Scala scripts per event on
    the JVM; here the device round-trips once per micro-batch instead)."""
    import numpy as _np

    import jax as _jax

    from . import event as ev

    pyfn = _build_script_callable(fd)
    args = [compile_expression(p, scope) for p in expr.parameters]
    rtype = (fd.return_type or "OBJECT").upper()
    out_dtype = ev.dtype_of(rtype)
    interner = scope.interner
    # every schema of an app shares one ObjectRegistry; OBJECT-typed script
    # arguments decode through it (None only for real nulls)
    objects = next((s.objects for s in scope._sources.values()
                    if getattr(s, "objects", None) is not None), None)
    arg_types = [a.type for a in args]

    def host(*arrs):
        arrs = [_np.asarray(a) for a in arrs]
        shape = _np.broadcast_shapes(*[a.shape for a in arrs]) if arrs else ()
        arrs = [_np.broadcast_to(a, shape) for a in arrs]
        flat = [a.reshape(-1) for a in arrs]
        n = flat[0].shape[0] if flat else 1
        out = _np.empty((n,), ev.np_dtype(rtype))
        for i in range(n):
            # reference scripts receive real nulls: the shared scalar
            # decode maps in-band null values to None at this boundary
            data = [ev.decode_scalar(t, a[i], interner, objects)
                    for a, t in zip(flat, arg_types)]
            r = pyfn(data)
            if rtype == "STRING":
                out[i] = interner.intern(None if r is None else str(r))
            elif r is None:
                # symmetric with the input decode: a script returning None
                # writes the return type's in-band null value
                out[i] = ev.null_value(rtype)
            else:
                out[i] = r
        return out.reshape(shape)

    def fn(env):
        vals = [a.fn(env) for a in args]
        vals = [jnp.asarray(v) for v in vals]
        shape = jnp.broadcast_shapes(*[v.shape for v in vals]) if vals else ()
        sds = _jax.ShapeDtypeStruct(shape, out_dtype)
        return _jax.pure_callback(host, sds, *vals, vmap_method="expand_dims")

    return CompiledExpr(fn, rtype)


# the built-in script engine registers through the same SPI custom engines
# use (reference: core ships the javascript Script extension the same way)
from .extension import script_engine as _script_engine  # noqa: E402

_script_engine("python", replace=True)(_python_script_engine)
_script_engine("py", replace=True)(_python_script_engine)
