"""Pattern / sequence matching as a vectorized slot-slab NFA.

Reference behavior (what): CORE/query/input/stream/state/* — chains of
Pre/Post state processors holding per-pending-StateEvent lists, supporting
`every`, count quantifiers <m:n>, logical and/or, absent (`not X for t`) and
`within` (StreamPreStateProcessor.java:363-403 is the per-event O(pending)
inner loop; StateInputStreamParser.java:76-146 builds the chain).

TPU-native design (how): a pattern compiles to a *linear chain of atoms*.
Runtime state is a fixed slab of P pending slots per key with captured event
columns per atom.  One `step` consumes a micro-batch laid out per key as
[K,E] (the host groups events by partition key): a lax.scan walks the E
event columns — sequential semantics within a key — and each tick evaluates
every chain position for every (key, slot) in parallel, so the reference's
O(pending × events) Java loop becomes a handful of [K,P] vector ops per
tick.  Forked continuations (count quantifiers, `every` seeds) allocate free
slots by masked ranking with drop-on-overflow; completions emit capture rows
consumed by the query selector.

Tick phase order (strict): within-expiry -> absent-deadline advance ->
match eval (pre-capture state) -> in-place capture -> emission gather ->
fork/seed spawn -> in-place advance / kill / deactivate.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax.numpy as jnp

from ..query_api.expression import Expression
from ..query_api.query import (
    AbsentStreamStateElement,
    CountStateElement,
    EveryStateElement,
    Filter,
    LogicalStateElement,
    NextStateElement,
    SingleInputStream,
    StateElement,
    StateInputStream,
    StreamStateElement,
)
from . import event as ev
from .executor import CompileError, CompiledExpr, Scope, compile_expression

BIG = jnp.iinfo(jnp.int64).max // 4


# ---------------------------------------------------------------------------
# Compilation: StateElement tree -> linear atom chain
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Atom:
    pos: int
    stream_id: str
    ref: str
    filter_expr: Optional[Expression]
    min_count: int = 1
    max_count: int = 1            # -1 == ANY
    absent: bool = False
    waiting_time: Optional[int] = None
    every: bool = False
    logical: Optional[str] = None  # 'AND' | 'OR' (self = side 0)
    partner: Optional["Atom"] = None
    capture_depth: int = 1

    @property
    def is_count(self) -> bool:
        return self.max_count != 1 or self.min_count != 1

    @property
    def ckey(self) -> str:
        return f"{self.pos}:{self.ref}"


@dataclasses.dataclass
class PatternSpec:
    atoms: List[Atom]
    state_type: str               # PATTERN | SEQUENCE
    within: Optional[int]
    count_cap: int = 8

    @property
    def n_states(self) -> int:
        return len(self.atoms)

    @property
    def stream_ids(self) -> List[str]:
        out = []
        for a in self.all_atoms():
            if a.stream_id not in out:
                out.append(a.stream_id)
        return out

    def all_atoms(self):
        for a in self.atoms:
            yield a
            if a.partner is not None:
                yield a.partner

    @property
    def has_absent(self) -> bool:
        """True when timer-driven absent machinery is needed: standalone
        `not X for t` atoms, or timed absent sides of logical pairs
        (instant `not A and B` needs no timers)."""
        return any(
            a.absent or (a.partner is not None and a.partner.absent and
                         a.partner.waiting_time is not None)
            for a in self.atoms)


def linearize(sis: StateInputStream, count_cap: int = 8) -> PatternSpec:
    atoms: List[Atom] = []

    def mk_atom(stream: SingleInputStream, pos: int, every: bool) -> Atom:
        filt = None
        for h in stream.stream_handlers:
            if isinstance(h, Filter):
                if filt is not None:
                    raise CompileError("multiple filters on a pattern element")
                filt = h.expression
            else:
                raise CompileError(
                    "windows/functions on pattern elements not supported")
        ref = stream.stream_reference_id or f"__p{pos}"
        return Atom(pos, stream.stream_id, ref, filt, every=every)

    def rec(el: StateElement, every: bool):
        if isinstance(el, NextStateElement):
            rec(el.state_element, every)
            rec(el.next_state_element, False)
        elif isinstance(el, EveryStateElement):
            rec(el.state_element, True)
        elif isinstance(el, StreamStateElement):
            atoms.append(mk_atom(el.basic_single_input_stream,
                                 len(atoms), every))
        elif isinstance(el, AbsentStreamStateElement):
            a = mk_atom(el.basic_single_input_stream, len(atoms), every)
            a.absent = True
            a.waiting_time = el.waiting_time
            if a.waiting_time is None:
                raise CompileError(
                    "absent pattern elements need 'for <time>' in this build")
            atoms.append(a)
        elif isinstance(el, CountStateElement):
            inner = el.stream_state_element
            a = mk_atom(inner.basic_single_input_stream, len(atoms), every)
            a.min_count = el.min_count
            a.max_count = el.max_count
            cap = count_cap if el.max_count == CountStateElement.ANY \
                else min(el.max_count, count_cap)
            a.capture_depth = max(cap, 1)
            atoms.append(a)
        elif isinstance(el, LogicalStateElement):
            def to_parts(x):
                if isinstance(x, StreamStateElement):
                    return x.basic_single_input_stream, False, None
                if isinstance(x, AbsentStreamStateElement):
                    return x.basic_single_input_stream, True, x.waiting_time
                raise CompileError(
                    "logical pattern sides must be plain or absent stream "
                    "elements")
            s1, ab1, wt1 = to_parts(el.stream_state_element_1)
            s2, ab2, wt2 = to_parts(el.stream_state_element_2)
            if ab1 and ab2:
                raise CompileError(
                    "both sides of a logical pattern cannot be absent")
            if (ab1 or ab2) and el.type == "OR":
                raise CompileError(
                    "'not X or Y' is not a valid pattern (reference: "
                    "logical absent combines with 'and' only)")
            pos = len(atoms)
            wt = wt1 if ab1 else wt2
            if (ab1 or ab2) and wt is not None and pos == 0:
                raise CompileError(
                    "leading 'not X for <time> and Y' is not supported in "
                    "this build (the wait clock starts at a preceding "
                    "stage); precede it with a stage or drop 'for <time>'")
            # the PRESENCE side is always the primary atom (it seeds and
            # captures); an absent side rides as the partner: its arrival
            # kills the pending state until the waiting time (if any) has
            # elapsed, after which the absence obligation is satisfied
            # (reference: AbsentLogicalPreStateProcessor)
            if ab1:
                a = mk_atom(s2, pos, every)
                b = mk_atom(s1, pos, False)
                b.absent = True
            else:
                a = mk_atom(s1, pos, every)
                b = mk_atom(s2, pos, False)
                b.absent = ab2
            b.waiting_time = wt if (ab1 or ab2) else None
            if b.ref == a.ref or b.ref == f"__p{pos}":
                b.ref = f"__p{pos}b"
            a.logical = el.type
            a.partner = b
            atoms.append(a)
        else:
            raise CompileError(
                f"unsupported pattern element {type(el).__name__}")

    rec(sis.state_element, False)
    if not atoms:
        raise CompileError("empty pattern")
    return PatternSpec(atoms, sis.state_type, sis.within_time,
                       count_cap=count_cap)


# ---------------------------------------------------------------------------
# Runtime
# ---------------------------------------------------------------------------

class PatternState(NamedTuple):
    """Per-key NFA slab.  The key axis K is LAST on every leaf so the whole
    state pipeline (blob [W,K] <-> leaves <-> tick ops) stays key-minor: K
    rides the TPU lane dimension and pack/unpack are pure reshapes, no
    transposes (a [K,P] convention cost ~80ms/step in layout churn at 131k
    keys)."""
    active: Any       # bool[P,K]
    pos: Any          # i32[P,K]
    count: Any        # i32[P,K] captures at current pos
    lmask: Any        # i32[P,K] logical sides satisfied (bit0/bit1)
    start_ts: Any     # i64[P,K]
    entry_ts: Any     # i64[P,K] ts of entering current pos
    seed_on: Any      # bool[K]
    done: Any         # bool[K]  non-every pattern already matched
    dropped: Any      # i64 scalar: forks dropped on slab overflow
    caps: Dict[str, Tuple]   # atom.ckey -> (ts[P,D,K], cols tuple [P,D,K])


class PatternExec:
    def __init__(self, spec: PatternSpec, schemas: Dict[str, ev.Schema],
                 interner: ev.StringInterner, slots: int = 8,
                 emit_refs: Optional[set] = None, script_functions=None):
        self.spec = spec
        self.schemas = schemas
        self.P = slots
        self.S = spec.n_states
        self.interner = interner
        # emission pruning: only captures referenced by the query's selector
        # are materialized into per-match output rows (None = all)
        self.emit_refs = emit_refs

        # selector-facing scope: every non-absent atom ref is a source
        self.scope = Scope()
        self.scope.interner = interner
        self.scope.script_functions = script_functions
        for a in spec.all_atoms():
            if not a.absent:
                self.scope.add_source(a.ref, schemas[a.stream_id])

        # per-atom filter scopes: unqualified attrs bind to the atom's OWN
        # stream (the incoming event); qualified refs reach earlier captures.
        # `x in Table` conditions compile to device probes against the
        # table's column snapshot, shipped into the step as in_tabs
        # (reference: InConditionExpressionExecutor inside NFA filters)
        self._filters: Dict[str, Optional[CompiledExpr]] = {}
        self.in_deps: List[str] = []
        for a in spec.all_atoms():
            if a.filter_expr is None:
                self._filters[a.ckey] = None
                continue
            fscope = Scope()
            fscope.interner = interner
            fscope.script_functions = script_functions
            fscope.add_source(a.ref, schemas[a.stream_id], default=True)
            for other in spec.all_atoms():
                if other.ckey != a.ckey and not other.absent:
                    fscope.add_source(other.ref, schemas[other.stream_id],
                                      default=False)
            from ..query_api.expression import In, walk
            for n in walk(a.filter_expr):
                if isinstance(n, In) and n.source_id not in self.in_deps:
                    self.in_deps.append(n.source_id)
            self._filters[a.ckey] = compile_expression(a.filter_expr, fscope)

    # -- state ----------------------------------------------------------------
    def init_state(self, K: int) -> PatternState:
        P = self.P
        caps: Dict[str, Tuple] = {}
        for a in self.spec.all_atoms():
            if a.absent:
                continue
            schema = self.schemas[a.stream_id]
            D = a.capture_depth
            # unfilled captures are NULL, not zero: an unmatched OR branch
            # and uncollected count rows (e1[i] beyond the collected depth)
            # emit null attributes (reference: LogicalPreStateProcessor
            # leaves the partner's StreamEvent null; e1[i] out of range
            # returns null)
            cols = tuple(
                jnp.full((P, D, K), ev.null_value(t), dtype=d)
                for t, d in zip(schema.types, schema.dtypes))
            # ts plane -1 == unfilled: fill-depth tests use >= 0, so a
            # legitimate playback event at timestamp 0 still counts
            caps[a.ckey] = (jnp.full((P, D, K), -1, jnp.int64), cols)
        return PatternState(
            active=jnp.zeros((P, K), jnp.bool_),
            pos=jnp.zeros((P, K), jnp.int32),
            count=jnp.zeros((P, K), jnp.int32),
            lmask=jnp.zeros((P, K), jnp.int32),
            start_ts=jnp.zeros((P, K), jnp.int64),
            entry_ts=jnp.zeros((P, K), jnp.int64),
            seed_on=jnp.ones((K,), jnp.bool_),
            done=jnp.zeros((K,), jnp.bool_),
            dropped=jnp.asarray(0, jnp.int64),
            caps=caps,
        )

    # -- one event per key ----------------------------------------------------
    def tick(self, st: PatternState, stream_id: str, ev_cols, ev_ts,
             ev_valid, now_k, in_tabs=()):
        spec = self.spec
        S = self.S
        P, K = st.active.shape
        a0 = spec.atoms[0]
        F = jnp.zeros((P, K), jnp.bool_)

        # ---- phase 1: within expiry ----------------------------------------
        if spec.within is not None:
            alive = now_k[None, :] - st.start_ts <= spec.within
            st = st._replace(active=jnp.logical_and(st.active, alive))

        # ---- phase 2: absent deadlines -------------------------------------
        absent_complete = F
        absent_ts = jnp.zeros((P, K), jnp.int64)
        for a in spec.atoms:
            if not a.absent:
                continue
            at_pos = jnp.logical_and(st.active, st.pos == a.pos)
            due = jnp.logical_and(
                at_pos, st.entry_ts + a.waiting_time <= now_k[None, :])
            if a.pos == S - 1:
                absent_complete = jnp.logical_or(absent_complete, due)
                absent_ts = jnp.where(due, st.entry_ts + a.waiting_time,
                                      absent_ts)
                st = st._replace(active=jnp.logical_and(
                    st.active, jnp.logical_not(due)))
            else:
                st = st._replace(
                    pos=jnp.where(due, a.pos + 1, st.pos).astype(jnp.int32),
                    count=jnp.where(due, 0, st.count).astype(jnp.int32),
                    lmask=jnp.where(due, 0, st.lmask).astype(jnp.int32),
                    entry_ts=jnp.where(due, st.entry_ts + a.waiting_time,
                                       st.entry_ts),
                )

        # timed logical-absent pairs (`not A for t and B`): when the wait
        # elapses without a matching A, the absence obligation is SATISFIED
        # (bit 2 in lmask); the state fires once B has also arrived —
        # whichever of {deadline, B} comes last triggers the completion
        for a in spec.atoms:
            p = a.partner
            if p is None or not p.absent or p.waiting_time is None:
                continue
            at_pos = jnp.logical_and(st.active, st.pos == a.pos)
            pend = jnp.logical_and(at_pos, (st.lmask & 2) == 0)
            due = jnp.logical_and(
                pend, st.entry_ts + p.waiting_time <= now_k[None, :])
            have_b = (st.lmask & 1) != 0
            fire = jnp.logical_and(due, have_b)
            st = st._replace(lmask=jnp.where(due, st.lmask | 2, st.lmask)
                             .astype(jnp.int32))
            if a.pos == S - 1:
                absent_complete = jnp.logical_or(absent_complete, fire)
                absent_ts = jnp.where(fire, st.entry_ts + p.waiting_time,
                                      absent_ts)
                st = st._replace(active=jnp.logical_and(
                    st.active, jnp.logical_not(fire)))
            else:
                st = st._replace(
                    pos=jnp.where(fire, a.pos + 1, st.pos).astype(jnp.int32),
                    count=jnp.where(fire, 0, st.count).astype(jnp.int32),
                    lmask=jnp.where(fire, 0, st.lmask).astype(jnp.int32),
                    entry_ts=jnp.where(fire, st.entry_ts + p.waiting_time,
                                       st.entry_ts),
                )

        # ---- phase 3: match evaluation (pre-capture state) -----------------
        env = self._build_env(st, stream_id, ev_cols, ev_ts, in_tabs)
        ev_ok = jnp.logical_and(ev_valid, jnp.logical_not(st.done))   # [K]

        advance_inplace = F
        complete = absent_complete
        deactivate = absent_complete
        fork = F
        kill = F
        matched_any = F
        capture: Dict[str, Any] = {}
        lmask_new = st.lmask
        # epsilon closure over zero-min count atoms (e1? / e1*): a thread
        # parked at position q that has collected NOTHING there may match a
        # later atom p directly when every atom in [q, p) is a plain count
        # with min_count == 0 (reference: a <0:n> state's next processor is
        # reachable without any occurrence).  Matched-from-skip threads
        # advance/collect AS IF at p, so the position updates below carry
        # explicit targets instead of pos+1.
        skip_srcs: Dict[int, List[int]] = {}
        for a_ in spec.atoms:
            srcs: List[int] = []
            if a_.logical is None and not a_.absent:
                q = a_.pos - 1
                while q >= 0 and spec.atoms[q].is_count \
                        and spec.atoms[q].min_count == 0 \
                        and spec.atoms[q].partner is None \
                        and not spec.atoms[q].absent:
                    srcs.append(q)
                    q -= 1
            skip_srcs[a_.pos] = srcs
        fork_tgt = st.pos + 1      # [P,K] forked continuation's position
        fork_cnt = jnp.zeros_like(st.count)   # forked slot's start count
        capture_here = {}          # captures owned by the slot's own
                                   # position (drives its count); skip
                                   # captures ride `capture` for forks/
                                   # emission but must not advance the
                                   # surviving origin's count
        skip_marks = {}            # atom ckey -> [P,K] skip-match mask:
                                   # after forks inherit, the surviving
                                   # origin reverts these captures to null

        def mark(d, key, m):
            d[key] = jnp.logical_or(d.get(key, F), m)

        for a in spec.atoms:
            last = a.pos == S - 1
            sides = [(a, 0)] + ([(a.partner, 1)] if a.partner else [])
            for atom, side in sides:
                if atom.stream_id != stream_id:
                    continue
                filt = self._filters[atom.ckey]
                if filt is None:
                    cond = jnp.ones((P, K), jnp.bool_)
                else:
                    # the atom under evaluation sees the INCOMING event under
                    # its own ref; other refs stay bound to captures (binding
                    # by stream id wrongly aliased e1.price to the current
                    # event for same-stream patterns)
                    env_a = dict(env)
                    env_a[atom.ref] = tuple(
                        jnp.broadcast_to(c[None, :], (P, K))
                        for c in ev_cols)
                    cond = jnp.broadcast_to(filt.fn(env_a), (P, K))
                at_here = jnp.logical_and(st.active, st.pos == a.pos)
                m_here = jnp.logical_and(jnp.logical_and(at_here, cond),
                                         ev_ok[None, :])
                m_skip = F
                if atom is a and skip_srcs.get(a.pos):
                    from_skip = F
                    for q2 in skip_srcs[a.pos]:
                        from_skip = jnp.logical_or(from_skip,
                                                   st.pos == q2)
                    from_skip = jnp.logical_and(
                        jnp.logical_and(st.active, from_skip),
                        st.count == 0)
                    m_skip = jnp.logical_and(
                        jnp.logical_and(from_skip, cond), ev_ok[None, :])
                m = jnp.logical_or(m_here, m_skip)
                if atom is a and skip_srcs.get(a.pos):
                    mark(skip_marks, atom.ckey, m_skip)
                if atom.absent:
                    # absence violated — unless the obligation was already
                    # satisfied (timed pair whose wait elapsed, bit 1<<side)
                    live = (st.lmask & (1 << side)) == 0
                    kill = jnp.logical_or(kill, jnp.logical_and(m, live))
                    continue
                matched_any = jnp.logical_or(matched_any, m)
                if a.logical is not None:
                    bit = 1 << side
                    have_other = (lmask_new & (3 ^ bit)) != 0
                    # only OR and INSTANT absent pairs advance on the
                    # presence side alone; AND-of-presences needs the other
                    # side's bit and TIMED absent pairs need the
                    # satisfied-absence bit the deadline pass sets — both
                    # ride have_other
                    pair_absent = a.partner is not None and a.partner.absent
                    instant_pair = pair_absent and \
                        a.partner.waiting_time is None
                    adv = m if (a.logical == "OR" or instant_pair) \
                        else jnp.logical_and(m, have_other)
                    lmask_new = jnp.where(m, lmask_new | bit, lmask_new)
                    mark(capture, atom.ckey, m)
                    mark(capture_here, atom.ckey, m)
                    if last:
                        complete = jnp.logical_or(complete, adv)
                        deactivate = jnp.logical_or(deactivate, adv)
                    else:
                        advance_inplace = jnp.logical_or(advance_inplace, adv)
                elif not a.is_count:
                    mark(capture, atom.ckey, m)
                    mark(capture_here, atom.ckey, m_here)
                    if last:
                        # skip-completions (m_skip) emit but do NOT kill the
                        # slot: the zero-collect continuation survives to
                        # keep collecting, mirroring the reference's
                        # separate pending state per interpretation
                        complete = jnp.logical_or(complete, m)
                        deactivate = jnp.logical_or(deactivate, m_here)
                    else:
                        advance_inplace = jnp.logical_or(advance_inplace,
                                                         m_here)
                        # skip-advances FORK a continuation at the target
                        # position; the collector stays where it was
                        fork = jnp.logical_or(fork, m_skip)
                        fork_tgt = jnp.where(m_skip, a.pos + 1, fork_tgt)
                        fork_cnt = jnp.where(m_skip, 0, fork_cnt)
                else:
                    newc = st.count + 1
                    maxc = spec.count_cap if a.max_count < 0 else a.max_count
                    can_stay = jnp.logical_and(m_here, newc < maxc)
                    can_adv = jnp.logical_and(m_here, newc >= a.min_count)
                    mark(capture, atom.ckey, m)
                    mark(capture_here, atom.ckey, m_here)
                    if last:
                        complete = jnp.logical_or(complete, can_adv)
                        if a.min_count <= 1:
                            # a skip-collect satisfies min on its first
                            # event: emit, but keep the origin slot alive
                            complete = jnp.logical_or(complete, m_skip)
                        deactivate = jnp.logical_or(
                            deactivate,
                            jnp.logical_and(can_adv, jnp.logical_not(can_stay)))
                    else:
                        fk = jnp.logical_and(can_adv, can_stay)
                        fork = jnp.logical_or(fork, fk)
                        fork_tgt = jnp.where(fk, a.pos + 1, fork_tgt)
                        ai = jnp.logical_and(can_adv,
                                             jnp.logical_not(can_stay))
                        advance_inplace = jnp.logical_or(advance_inplace, ai)
                    # skip-collect into a count atom: fork a collector at
                    # the target position that already HOLDS this event
                    # (captures inherit; count starts at 1); the
                    # zero-collect origin survives.  Known limitation: a
                    # slot firing BOTH an own-position count fork and a
                    # skip fork on one event keeps only the skip fork
                    # (single fork candidate per slot)
                    fork = jnp.logical_or(fork, m_skip)
                    fork_tgt = jnp.where(m_skip, a.pos, fork_tgt)
                    fork_cnt = jnp.where(m_skip, 1, fork_cnt)

        # SEQUENCE: strict continuity
        if spec.state_type == "SEQUENCE":
            no_match = jnp.logical_and(
                st.active,
                jnp.logical_and(ev_ok[None, :], jnp.logical_not(matched_any)))
            kill = jnp.logical_or(kill, no_match)

        # ---- seed (virtual pending slot at position 0) ---------------------
        # an absent FIRST side (`not A and B` at position 0): A's arrival
        # disarms the virtual seed (non-every; `every` re-arms immediately,
        # so the arrival has no lasting effect there — reference:
        # AbsentLogicalPreStateProcessor restart semantics)
        if a0.partner is not None and a0.partner.absent and \
                a0.partner.stream_id == stream_id and not a0.every:
            patom = a0.partner
            pfilt = self._filters[patom.ckey]
            if pfilt is None:
                pc = jnp.ones((K,), jnp.bool_)
            else:
                env_p = dict(env)
                env_p[patom.ref] = tuple(
                    jnp.broadcast_to(cc[None, :], st.active.shape)
                    for cc in ev_cols)
                pc = _seed_eval(pfilt, env_p, K)
            disarm = jnp.logical_and(jnp.logical_and(st.seed_on, ev_ok), pc)
            st = st._replace(seed_on=jnp.logical_and(
                st.seed_on, jnp.logical_not(disarm)))
        seed_match = jnp.zeros((K,), jnp.bool_)
        seed_side = jnp.zeros((K,), jnp.int32)
        for atom, side in [(a0, 0)] + ([(a0.partner, 1)] if a0.partner else []):
            if atom is None or atom.stream_id != stream_id or a0.absent \
                    or atom.absent:
                continue
            filt = self._filters[atom.ckey]
            if filt is None:
                c = jnp.ones((K,), jnp.bool_)
            else:
                env_s = dict(env)
                env_s[atom.ref] = tuple(
                    jnp.broadcast_to(cc[None, :], st.active.shape)
                    for cc in ev_cols)
                c = _seed_eval(filt, env_s, K)
            sm = jnp.logical_and(jnp.logical_and(st.seed_on, ev_ok), c)
            seed_side = jnp.where(
                jnp.logical_and(sm, jnp.logical_not(seed_match)), side,
                seed_side)
            seed_match = jnp.logical_or(seed_match, sm)

        # a seed advances immediately iff the first atom completes with one
        # event: single non-count atom, count with min<=1, or logical OR
        if a0.logical is not None:
            seed_immediate = a0.logical == "OR" or (
                a0.partner is not None and a0.partner.absent)
        elif a0.is_count:
            seed_immediate = a0.min_count <= 1
        else:
            seed_immediate = True
        # ...and keeps a collecting continuation iff a count atom can take more
        seed_keeps = a0.is_count and (a0.max_count < 0 or a0.max_count > 1)

        seed_complete = jnp.logical_and(
            seed_match, jnp.asarray(seed_immediate and S == 1))
        # seed epsilon skip: when EVERY atom before the last is a plain
        # zero-min count, an event matching the last atom completes the
        # whole pattern from the virtual seed with all earlier captures
        # null (e.g. `e1=A?, e2=B` firing on a lone B)
        last_atom = spec.atoms[S - 1]
        seed_skip_possible = (
            S > 1 and len(skip_srcs.get(S - 1, ())) == S - 1 and
            last_atom.logical is None and not last_atom.absent and
            (not last_atom.is_count or last_atom.min_count <= 1))
        seed_skip_hit = jnp.zeros((K,), jnp.bool_)
        if seed_skip_possible and last_atom.stream_id == stream_id:
            lfilt = self._filters[last_atom.ckey]
            if lfilt is None:
                lc = jnp.ones((K,), jnp.bool_)
            else:
                env_l = dict(env)
                env_l[last_atom.ref] = tuple(
                    jnp.broadcast_to(cc[None, :], st.active.shape)
                    for cc in ev_cols)
                # the zero-occurrence interpretation carries NO captures:
                # references to the skipped atoms read null, so a filter
                # like `price > e1[0].price` correctly rejects it
                for aa in spec.all_atoms():
                    if aa.absent or aa is last_atom:
                        continue
                    a_sch = self.schemas[aa.stream_id]
                    nulls = tuple(
                        jnp.full((P, K), ev.null_value(t), d)
                        for t, d in zip(a_sch.types, a_sch.dtypes))
                    env_l[aa.ref] = nulls
                    for di in range(aa.capture_depth):
                        env_l[f"{aa.ref}@{di}"] = nulls
                    env_l[f"{aa.ref}@-1"] = nulls
                lc = _seed_eval(lfilt, env_l, K)
            seed_skip_hit = jnp.logical_and(
                jnp.logical_and(st.seed_on, ev_ok), lc)
            seed_complete = jnp.logical_or(seed_complete, seed_skip_hit)
        seed_spawn = jnp.logical_and(seed_match, jnp.asarray(
            (seed_immediate and S > 1) or not seed_immediate or seed_keeps))
        # spawned seed slot's position / count
        if seed_immediate and not seed_keeps:
            seed_pos, seed_count = 1, 0
        else:
            seed_pos, seed_count = 0, 1
        seed_fork_also = seed_immediate and seed_keeps and S > 1
        # (count atom with min<=1,max>1 at pos 0: one slot advances, one
        #  collects => spawn up to 2; handled by a second seed candidate)

        if not a0.every:
            st = st._replace(seed_on=jnp.logical_and(
                st.seed_on, jnp.logical_not(seed_match)))
            newly_done = jnp.logical_or(jnp.any(complete, axis=0),
                                        seed_complete)
            st = st._replace(done=jnp.logical_or(st.done, newly_done))

        st = st._replace(lmask=lmask_new)

        # ---- phase 4: in-place capture -------------------------------------
        newcaps = {}
        for a in spec.all_atoms():
            if a.absent:
                continue
            ck = a.ckey
            ts_c, cols_c = st.caps[ck]
            here = capture.get(ck)
            if here is None:
                newcaps[ck] = (ts_c, cols_c)
                continue
            D = ts_c.shape[1]
            idx = jnp.clip(st.count, 0, D - 1)
            ncols = tuple(
                _set_along(c, idx, jnp.broadcast_to(
                    ev_cols[j][None, :], idx.shape), here)
                for j, c in enumerate(cols_c))
            nts = _set_along(ts_c, idx, jnp.broadcast_to(
                ev_ts[None, :], idx.shape), here)
            newcaps[ck] = (nts, ncols)
        st = st._replace(caps=newcaps)

        # ---- phase 5: emission gather ([P+1, K]: slot axis + seed row) -----
        emit_mask = jnp.concatenate([complete, seed_complete[None, :]], axis=0)
        emit_ts = jnp.concatenate([
            jnp.where(absent_complete, absent_ts,
                      jnp.broadcast_to(ev_ts[None, :], (P, K))),
            ev_ts[None, :]], axis=0)                      # [P+1,K]
        emit_count = jnp.concatenate(
            [jnp.where(complete, st.count + jnp.where(
                capture_any(capture, F), 1, 0), 0),
             jnp.ones((1, K), jnp.int32)], axis=0)
        emit: Dict[str, Any] = {"mask": emit_mask, "ts": emit_ts,
                                "count": emit_count}
        for a in spec.all_atoms():
            if a.absent:
                continue
            if self.emit_refs is not None and a.ref not in self.emit_refs:
                continue
            ck = a.ckey
            ts_c, cols_c = st.caps[ck]
            D = ts_c.shape[1]
            # the seed emission row's captured atom: position 0 for a
            # single-atom pattern; the LAST atom for an epsilon-skip
            # completion (every earlier capture emits null)
            if S == 1:
                is_seed_cap = (a.pos == 0 and a.stream_id == stream_id)
            else:
                is_seed_cap = (seed_skip_possible and a.pos == S - 1 and
                               a.stream_id == stream_id)
            a_schema2 = self.schemas[a.stream_id]
            seed_cols = tuple(
                jnp.broadcast_to(ev_cols[j][None, None, :], (1, D, K))
                if is_seed_cap else
                jnp.full((1, D, K), ev.null_value(t), c.dtype)
                for j, (c, t) in enumerate(
                    zip(cols_c, a_schema2.types)))
            emit[ck] = (
                jnp.concatenate(
                    [ts_c, jnp.broadcast_to(ev_ts[None, None, :], (1, D, K))
                     if is_seed_cap else jnp.full((1, D, K), -1, jnp.int64)],
                    axis=0),
                tuple(jnp.concatenate([c, sc], axis=0)
                      for c, sc in zip(cols_c, seed_cols)))

        # ---- phase 6: spawn forks + seed -----------------------------------
        st = self._spawn(st, fork, fork_tgt, fork_cnt, seed_spawn,
                         seed_pos, seed_count, seed_side, seed_fork_also,
                         stream_id, ev_cols, ev_ts, a0)

        # surviving zero-collect origins revert skip-written captures to
        # null AFTER emission (phase 5) and fork inheritance (phase 6)
        # consumed them: a later fork from the origin must not carry a
        # capture that belongs to the skipped interpretation only
        if skip_marks:
            newcaps2 = dict(st.caps)
            for a in spec.all_atoms():
                msk = skip_marks.get(a.ckey)
                if msk is None or a.absent:
                    continue
                ts_c, cols_c = st.caps[a.ckey]
                D2 = ts_c.shape[1]
                idx2 = jnp.clip(st.count, 0, D2 - 1)
                a_sch = self.schemas[a.stream_id]
                nts2 = _set_along(ts_c, idx2, jnp.full(idx2.shape, -1,
                                                       jnp.int64), msk)
                ncols2 = tuple(
                    _set_along(c, idx2,
                               jnp.full(idx2.shape, ev.null_value(t),
                                        c.dtype), msk)
                    for c, t in zip(cols_c, a_sch.types))
                newcaps2[a.ckey] = (nts2, ncols2)
            st = st._replace(caps=newcaps2)

        # ---- phase 7: in-place advance / kill / deactivate -----------------
        captured_now = capture_any(capture_here, F)
        st = st._replace(
            count=jnp.where(advance_inplace | deactivate, 0,
                            jnp.where(captured_now, st.count + 1,
                                      st.count)).astype(jnp.int32),
            pos=jnp.where(advance_inplace, st.pos + 1,
                          st.pos).astype(jnp.int32),
            lmask=jnp.where(advance_inplace, 0, st.lmask).astype(jnp.int32),
            entry_ts=jnp.where(advance_inplace, ev_ts[None, :], st.entry_ts),
            active=jnp.logical_and(
                st.active,
                jnp.logical_not(jnp.logical_or(kill, deactivate))),
        )
        return st, emit

    # -- spawn ----------------------------------------------------------------
    def _spawn(self, st: PatternState, fork, fork_tgt, fork_cnt, seed_spawn,
               seed_pos, seed_count, seed_side, seed_fork_also, stream_id,
               ev_cols, ev_ts, a0):
        """Allocate free slots for fork/seed candidates.

        Scatter-free formulation (TPU scatters serialize; gathers don't):
        instead of scattering candidates into target slots, each destination
        slot PULLS its candidate.  Slot j (if free) has free-rank r_j; the
        candidate with allocation-rank r_j lands there.  The rank->candidate
        inverse is a one-hot contraction over the tiny NC=P+2 axis, then all
        payload moves are take_along_axis gathers."""
        P, K = st.active.shape
        spec = self.spec

        # candidates: P slot-forks + seed (+ optional second seed continuation)
        extra = 2 if seed_fork_also else 1
        NC = P + extra
        seed2 = jnp.logical_and(seed_spawn, jnp.asarray(seed_fork_also))
        if seed_fork_also:
            cand_valid = jnp.concatenate(
                [fork, seed_spawn[None, :], seed2[None, :]], axis=0)
        else:
            cand_valid = jnp.concatenate([fork, seed_spawn[None, :]], axis=0)

        rank = jnp.cumsum(cand_valid.astype(jnp.int32), axis=0) - 1  # [NC,K]
        free = jnp.logical_not(st.active)                            # [P,K]
        free_rank = jnp.cumsum(free.astype(jnp.int32), axis=0) - 1   # [P,K]
        nfree = jnp.sum(free.astype(jnp.int32), axis=0)              # [K]
        ncand = jnp.sum(cand_valid.astype(jnp.int32), axis=0)

        # destination slot j takes candidate c iff free[j] and
        # rank[c] == free_rank[j] (and candidate exists)
        hot = jnp.logical_and(
            jnp.logical_and(cand_valid[None, :, :],
                            rank[None, :, :] == free_rank[:, None, :]),
            free[:, None, :])                                        # [P,NC,K]
        has_cand = jnp.any(hot, axis=1)                              # [P,K]

        st = st._replace(dropped=st.dropped + jnp.sum(
            jnp.maximum(ncand - nfree, 0).astype(jnp.int64)))

        def pull(cand_field, old_field):
            # one-hot contraction over the tiny NC axis; a take_along_axis
            # here compiles to an element-serialized TPU gather (measured
            # 180ms/step at 131k keys — the whole step budget)
            got = oh_take(cand_field[None, :, :], hot, 1)
            return jnp.where(has_cand, got, old_field)

        # candidate payloads [NC,K]
        fork_pos = fork_tgt    # a.pos+1 of the matched atom (skip-aware)
        if seed_fork_also:
            # first seed candidate: advancing slot (pos 1); second: collector
            cpos = jnp.concatenate(
                [fork_pos,
                 jnp.full((1, K), 1, jnp.int32),
                 jnp.full((1, K), 0, jnp.int32)], axis=0)
            ccount = jnp.concatenate(
                [fork_cnt.astype(jnp.int32),
                 jnp.zeros((1, K), jnp.int32),
                 jnp.ones((1, K), jnp.int32)], axis=0)
        else:
            cpos = jnp.concatenate(
                [fork_pos, jnp.full((1, K), seed_pos, jnp.int32)], axis=0)
            ccount = jnp.concatenate(
                [fork_cnt.astype(jnp.int32),
                 jnp.full((1, K), seed_count, jnp.int32)], axis=0)
        # lmask only matters while the seed STAYS at position 0 collecting
        # the other logical side; an immediately-advancing seed (OR, or
        # AND-with-absent) must start its next position with a CLEAN mask —
        # residue bits corrupt the absent/logical logic of position 1
        seed_lmask = jnp.where(
            seed_spawn, jnp.left_shift(jnp.ones((K,), jnp.int32), seed_side),
            0)[None, :] if (a0.logical is not None and seed_pos == 0) \
            else jnp.zeros((1, K), jnp.int32)
        clmask = jnp.concatenate(
            [jnp.zeros((P, K), jnp.int32)] + [seed_lmask] * extra, axis=0)
        cstart = jnp.concatenate(
            [st.start_ts] + [ev_ts[None, :]] * extra, axis=0)
        centry = jnp.broadcast_to(ev_ts[None, :], (NC, K))

        st = st._replace(
            active=jnp.logical_or(st.active, has_cand),
            pos=pull(cpos, st.pos),
            count=pull(ccount, st.count),
            lmask=pull(clmask, st.lmask),
            start_ts=pull(cstart, st.start_ts),
            entry_ts=pull(centry, st.entry_ts),
        )

        # captures: forks inherit the source slot (post-capture state, which
        # already includes this event); seeds get the incoming event at atom0
        newcaps = {}
        # fork candidate c (< P) sources from slot c; seed candidates are the
        # trailing `extra` rows.  All moves are one-hot contractions over
        # the tiny candidate/slot axes (TPU-serialized gathers avoided).
        seed_taken = jnp.any(hot[:, P:, :], axis=1)              # [P,K]
        fork_hot = hot[:, :P, :]                                 # [P(dst),P(src),K]
        fork_taken = jnp.logical_and(has_cand, jnp.logical_not(seed_taken))
        for a in spec.all_atoms():
            if a.absent:
                continue
            ck = a.ckey
            ts_c, cols_c = st.caps[ck]
            D = ts_c.shape[1]
            seed_has = (a.pos == 0 and a.stream_id == stream_id)
            first_d = (jnp.arange(D) == 0)[None, :, None]
            seed_m = jnp.logical_and(seed_taken[:, None, :],
                                     jnp.ones((1, D, 1), jnp.bool_))

            def merge(c, incoming, nullv):
                # c [P,D,K]; inherited[p,d,k] = sum_src hot[p,src,k]*c[src,d,k]
                inherited = oh_take(c[None, :, :, :],
                                    fork_hot[:, :, None, :], 1)  # [P,D,K]
                out = jnp.where(fork_taken[:, None, :], inherited, c)
                # a recycled seed slot's stale captures clear to NULL (not
                # zero): unfilled branches must decode as null attributes
                clear = jnp.full_like(out, nullv) if nullv is not None \
                    else jnp.zeros_like(out)
                if seed_has:
                    iv = jnp.broadcast_to(incoming[None, None, :],
                                          (P, D, K)).astype(c.dtype)
                    out = jnp.where(
                        jnp.logical_and(seed_m, first_d), iv,
                        jnp.where(seed_m, clear, out))
                else:
                    out = jnp.where(seed_m, clear, out)
                return out

            a_schema = self.schemas[a.stream_id]
            newcaps[ck] = (merge(ts_c, ev_ts, -1),
                           tuple(merge(c, ev_cols[j], ev.null_value(t))
                                 for j, (c, t) in enumerate(
                                     zip(cols_c, a_schema.types))))
        return st._replace(caps=newcaps)

    # -- env ------------------------------------------------------------------
    def _build_env(self, st: PatternState, stream_id: str, ev_cols, ev_ts,
                   in_tabs=()):
        env: Dict[str, Any] = {"__ts__": ev_ts[None, :]}
        # `x in Table` probes: one dense compare against the table's first
        # column snapshot, broadcasting over whatever shape the filter's
        # operand carries ([P,K] slabs here, [B] in plain queries)
        for dep, (tcol0, tvalid) in zip(self.in_deps, in_tabs):
            def probe(vals, _tc=tcol0, _tv=tvalid):
                return jnp.any(
                    jnp.logical_and(vals[..., None] == _tc, _tv), axis=-1)
            env["__in__:" + dep] = probe
        for a in self.spec.all_atoms():
            if a.absent:
                continue
            ts_c, cols_c = st.caps[a.ckey]       # [P,D,K]
            D = ts_c.shape[1]
            env[a.ref] = tuple(c[:, 0, :] for c in cols_c)
            for i in range(D):
                env[f"{a.ref}@{i}"] = tuple(c[:, i, :] for c in cols_c)
            # e1[last]: the deepest FILLED capture row.  st.count is
            # position-local (resets when a fork advances past the count
            # atom), so the fill depth derives from the capture ts plane
            # itself (real event timestamps are > 0; unfilled rows keep
            # their zero init)
            nfill = jnp.sum((ts_c >= 0).astype(jnp.int32), axis=1)  # [P,K]
            last_i = jnp.clip(nfill - 1, 0, D - 1)
            last_oh = jnp.arange(D)[None, :, None] == last_i[:, None, :]
            env[f"{a.ref}@-1"] = tuple(oh_take(c, last_oh, 1)
                                       for c in cols_c)
        return env


def oh_take(c, oh, axis):
    """Gather along a tiny axis as a one-hot contraction (select + reduce).
    TPU-friendly replacement for take_along_axis, whose generic gather
    lowers to element-serialized DMA on TPU."""
    if c.dtype == jnp.bool_:
        return jnp.any(jnp.logical_and(oh, c), axis=axis)
    return jnp.sum(jnp.where(oh, c, jnp.zeros((), c.dtype)), axis=axis,
                   dtype=c.dtype)


def capture_any(capture: Dict[str, Any], F):
    out = F
    for m in capture.values():
        out = jnp.logical_or(out, m)
    return out


def _seed_eval(filt: CompiledExpr, env, K):
    v = filt.fn(env)
    v = jnp.broadcast_to(v, v.shape if v.ndim else (K,))
    if v.ndim == 2:     # [P,K] -> any slot row works; captures are zeroed
        return v[0, :]
    return v


def _set_along(arr, idx, vals, mask):
    """arr[p, idx[p,k], k] = vals[p,k] where mask[p,k]; arr is [P,D,K]."""
    hit = jnp.logical_and(
        jnp.arange(arr.shape[1])[None, :, None] == idx[:, None, :],
        mask[:, None, :])
    return jnp.where(hit, vals[:, None, :].astype(arr.dtype), arr)
