from . import event
from .event import Event, EventBatch
from .runtime import (
    InputHandler,
    QueryCallback,
    SiddhiAppRuntime,
    SiddhiManager,
    StreamCallback,
)
