"""Debugger: breakpoints at query IN/OUT terminals, blocking the event
thread until next()/play() (reference: CORE/debugger/SiddhiDebugger.java:36 —
acquireBreakPoint :95, checkBreakPoint :133-169; wired into
ProcessStreamReceiver.receive :100-126 in the reference, here into the
query runtimes' staged-batch entry and the delivery path)."""
from __future__ import annotations

import threading
from typing import Callable, Optional, Set, Tuple


class SiddhiDebugger:
    IN = "IN"
    OUT = "OUT"

    def __init__(self, app):
        self.app = app
        self._breakpoints: Set[Tuple[str, str]] = set()
        self._callback: Optional[Callable] = None
        self._resume = threading.Event()
        self._step_mode = False
        self._lock = threading.RLock()

    # -- control (called from the debugging thread) ---------------------------
    def acquire_break_point(self, query_name: str, terminal: str) -> None:
        with self._lock:
            self._breakpoints.add((query_name, terminal))

    acquireBreakPoint = acquire_break_point

    def release_break_point(self, query_name: str, terminal: str) -> None:
        with self._lock:
            self._breakpoints.discard((query_name, terminal))

    releaseBreakPoint = release_break_point

    def release_all_break_points(self) -> None:
        with self._lock:
            self._breakpoints.clear()

    releaseAllBreakPoints = release_all_break_points

    def set_debugger_callback(self, cb: Callable) -> None:
        """cb(events, query_name, terminal, debugger)"""
        self._callback = cb

    setDebuggerCallback = set_debugger_callback

    def next(self) -> None:
        """Resume and break at the very next checkpoint."""
        with self._lock:
            self._step_mode = True
        self._resume.set()

    def play(self) -> None:
        """Resume until the next registered breakpoint."""
        with self._lock:
            self._step_mode = False
        self._resume.set()

    # -- checkpoint (called from the event thread) ----------------------------
    def check_break_point(self, query_name: str, terminal: str,
                          events) -> None:
        with self._lock:
            hit = self._step_mode or \
                (query_name, terminal) in self._breakpoints
        if not hit:
            return
        with self._lock:
            self._step_mode = False
        self._resume.clear()
        if self._callback is not None:
            self._callback(events, query_name, terminal, self)
        self._resume.wait()
