"""Incremental time-granularity aggregation.

Reference behavior (what): CORE/aggregation/AggregationRuntime.java:81,
IncrementalExecutor.java:48 (execute :102-130), AggregationParser.java —
`define aggregation A from S select g, avg(x) as ax ... group by g
aggregate by ts every sec...year` maintains running aggregates per duration
bucket (seconds..years); avg decomposes into sum+count base attributes
(incremental/AvgIncrementalAttributeAggregator.java:57-95); queries join
against a duration's buckets `within` a time range (`per "days"`).

TPU-native design (how): the reference cascades one executor per duration,
rolling finer buckets into coarser on rollover.  Here the device computes the
per-event base values (compiled expression stack -> [n_base, B] block); the
host merges per-(group, bucket) partials — computed with vectorized
np.unique/ufunc.at — into one dict store per duration.  No cascade is needed:
sum/count/min/max merge identically into every duration directly.  Join and
on-demand reads materialize a padded columnar snapshot (AGG_TIMESTAMP + the
declared outputs) that drops into the existing table-join device path.
"""
from __future__ import annotations

import calendar
import datetime
import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..query_api.expression import Constant, Expression, Variable
from . import event as ev
from .executor import CompileError, Scope, compile_expression

DURATION_MS = {
    "SECONDS": 1000,
    "MINUTES": 60_000,
    "HOURS": 3_600_000,
    "DAYS": 86_400_000,
    # MONTHS / YEARS are calendar-based; handled specially
}

_DUR_ALIASES = {
    "sec": "SECONDS", "second": "SECONDS", "seconds": "SECONDS",
    "min": "MINUTES", "minute": "MINUTES", "minutes": "MINUTES",
    "hour": "HOURS", "hours": "HOURS",
    "day": "DAYS", "days": "DAYS",
    "month": "MONTHS", "months": "MONTHS",
    "year": "YEARS", "years": "YEARS",
}


def normalize_duration(name: str) -> str:
    d = _DUR_ALIASES.get(name.strip().lower())
    if d is None:
        raise CompileError(f"unknown aggregation duration {name!r}")
    return d


def truncate_buckets(ts_ms: np.ndarray, duration: str) -> np.ndarray:
    """Bucket start per timestamp (vectorized; calendar months/years via
    per-unique conversion, matching the reference's calendar semantics —
    IncrementalUnixTimeFunctionUtil)."""
    if duration in DURATION_MS:
        d = DURATION_MS[duration]
        return (ts_ms // d) * d
    uniq, inv = np.unique(ts_ms, return_inverse=True)
    outs = np.empty_like(uniq)
    for i, t in enumerate(uniq):
        dt = datetime.datetime.fromtimestamp(t / 1000.0, datetime.timezone.utc)
        if duration == "MONTHS":
            dt = dt.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        else:  # YEARS
            dt = dt.replace(month=1, day=1, hour=0, minute=0, second=0,
                            microsecond=0)
        outs[i] = int(calendar.timegm(dt.timetuple()) * 1000)
    return outs[inv]


_DATE_FIELDS = ("year", "month", "day", "hour", "minute", "second")


def _parse_date_string(s: str) -> Tuple[int, Optional[str]]:
    """Parse `yyyy-MM-dd HH:mm:ss` (components optional from the right, or
    `**` wildcards) -> (epoch_ms_start, wildcard_field | None).
    Reference: within-clause time formats, aggregation docs."""
    s = s.strip()
    import re
    m = re.match(
        r"^(\d{4}|\*\*)(?:-(\d{1,2}|\*\*))?(?:-(\d{1,2}|\*\*))?"
        r"(?:[ T](\d{1,2}|\*\*))?(?::(\d{1,2}|\*\*))?(?::(\d{1,2}|\*\*))?",
        s)
    if not m or m.group(1) == "**":
        raise CompileError(f"cannot parse within-time {s!r}")
    vals = []
    wildcard = None
    for i, g in enumerate(m.groups()):
        if g is None or g == "**":
            if wildcard is None:
                wildcard = _DATE_FIELDS[i]
            vals.append(None)
        else:
            if wildcard is not None:
                raise CompileError(
                    f"non-wildcard after wildcard in {s!r}")
            vals.append(int(g))
    y = vals[0]
    dt = datetime.datetime(
        y, vals[1] or 1, vals[2] or 1, vals[3] or 0, vals[4] or 0,
        vals[5] or 0)
    return int(calendar.timegm(dt.timetuple()) * 1000), wildcard


def _advance(dt_ms: int, field: str) -> int:
    dt = datetime.datetime.fromtimestamp(dt_ms / 1000.0, datetime.timezone.utc)
    if field == "year":
        dt = dt.replace(year=dt.year + 1)
    elif field == "month":
        dt = dt.replace(year=dt.year + (dt.month == 12),
                        month=dt.month % 12 + 1)
    else:
        delta = {"day": 86_400, "hour": 3_600, "minute": 60, "second": 1}
        return dt_ms + delta[field] * 1000
    return int(calendar.timegm(dt.timetuple()) * 1000)


def _bound_of(expr) -> Tuple[int, Optional[str]]:
    if isinstance(expr, Constant):
        if expr.type in ("LONG", "INT"):
            return int(expr.value), None
        if expr.type == "STRING":
            return _parse_date_string(str(expr.value))
    raise CompileError(
        "within bounds must be time-string or epoch-ms constants")


def parse_within(within) -> Tuple[int, int]:
    """within '2020-01-01 ...' [, '2020-02-01 ...'] -> [start, end) ms."""
    if within is None:
        raise CompileError(
            "aggregation reads need a `within` clause (reference: "
            "AggregationRuntime.compileExpression)")
    if isinstance(within, tuple):
        s, _ = _bound_of(within[0])
        e, _ = _bound_of(within[1])
        return s, e
    s, wildcard = _bound_of(within)
    if wildcard is None:
        # single full timestamp: that instant's smallest covered unit
        return s, _advance(s, "second")
    return s, _advance(s, {"month": "year", "day": "month",
                           "hour": "day", "minute": "hour",
                           "second": "minute"}[wildcard])


def parse_per(per) -> str:
    if per is None:
        raise CompileError("aggregation reads need a `per` duration")
    if isinstance(per, Constant) and per.type == "STRING":
        return normalize_duration(str(per.value))
    if isinstance(per, Variable):
        return normalize_duration(per.attribute_name)
    raise CompileError("per must be a duration name")


class _BaseAgg:
    """One base (decomposed) aggregation: a compiled value expression and a
    merge rule."""

    def __init__(self, kind: str, value_fn, dtype):
        self.kind = kind          # 'sum' | 'count' | 'min' | 'max'
        self.value_fn = value_fn  # env -> [B] values (None for count)
        self.dtype = dtype

    def identity(self) -> float:
        if self.kind == "min":
            return np.inf
        if self.kind == "max":
            return -np.inf
        return 0.0

    def merge(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        if self.kind == "min":
            return np.minimum(a, b)
        if self.kind == "max":
            return np.maximum(a, b)
        return a + b

    def np_reduce_at(self, acc: np.ndarray, idx: np.ndarray,
                     vals: np.ndarray) -> None:
        if self.kind == "min":
            np.minimum.at(acc, idx, vals)
        elif self.kind == "max":
            np.maximum.at(acc, idx, vals)
        else:
            np.add.at(acc, idx, vals)


class _Output:
    """One declared output attribute and how to finalize it from base
    values (reference: IncrementalAttributeAggregator SPI)."""

    def __init__(self, name: str, attr_type: str, kind: str,
                 base_idx: Tuple[int, ...], group_pos: int = -1):
        self.name = name
        self.type = attr_type
        self.kind = kind          # 'group' | 'sum' | 'count' | 'min' | 'max' | 'avg'
        self.base_idx = base_idx
        self.group_pos = group_pos  # index into group key tuple for 'group'

    def finalize(self, base: np.ndarray) -> np.ndarray:
        """base: [n_rows, n_base] -> [n_rows] output column."""
        if self.kind == "avg":
            s, c = base[:, self.base_idx[0]], base[:, self.base_idx[1]]
            return np.where(c > 0, s / np.maximum(c, 1), 0.0)
        return base[:, self.base_idx[0]]


class AggregationRuntime:
    """Host+device runtime for one `define aggregation`."""

    def __init__(self, adef, app):
        self.definition = adef
        self.app = app
        sis = adef.basic_single_input_stream
        self.input_stream_id = sis.unique_stream_id
        schema = app.schemas.get(self.input_stream_id)
        if schema is None:
            raise CompileError(
                f"aggregation {adef.id!r}: undefined stream "
                f"{self.input_stream_id!r}")
        self.in_schema = schema
        self._lock = threading.RLock()

        scope = Scope()
        scope.interner = app.interner
        scope.add_source(self.input_stream_id, schema,
                         alias=sis.stream_reference_id)

        # filters on the input stream
        from ..query_api.query import Filter
        self._filters = []
        for h in sis.stream_handlers:
            if isinstance(h, Filter):
                c = compile_expression(h.expression, scope)
                if c.type != "BOOL":
                    raise CompileError("aggregation filter must be boolean")
                self._filters.append(c)
            else:
                raise CompileError(
                    "aggregation input supports filters only")

        # group-by columns
        self.group_names = [v.attribute_name
                            for v in (adef.selector.group_by_list or [])]
        self.group_positions = [schema.position(n) for n in self.group_names]
        self.group_types = [schema.types[p] for p in self.group_positions]

        # aggregate-by timestamp attribute (or event ts)
        self.ts_pos = -1
        if adef.aggregate_attribute is not None:
            self.ts_pos = schema.position(
                adef.aggregate_attribute.attribute_name)

        # decompose selection into base aggregations + outputs
        self.base: List[_BaseAgg] = []
        self.outputs: List[_Output] = []
        self._decompose(adef.selector, scope)

        self.durations = [normalize_duration(d) for d in adef.time_periods] \
            or ["SECONDS"]
        # store per duration: {(gkey..., bucket_start): np.ndarray[n_base]}
        self.stores: Dict[str, Dict[tuple, np.ndarray]] = {
            d: {} for d in self.durations}

        # device step: batch -> (valid mask, stacked base values)
        filters = self._filters
        base = self.base
        sid = self.input_stream_id

        def step(ts, kind, valid, cols, now):
            env = {sid: cols, "__ts__": ts, "__now__": now}
            keep = jnp.logical_and(valid, kind == ev.CURRENT)
            for f in filters:
                keep = jnp.logical_and(keep, f.fn(env))
            vals = []
            for b in base:
                if b.value_fn is None:
                    vals.append(jnp.ones(ts.shape, jnp.float64))
                else:
                    vals.append(jnp.asarray(b.value_fn(env), jnp.float64))
            return keep, jnp.stack(vals) if vals else jnp.zeros((0,) + ts.shape)

        self._step = jax.jit(step)

    # -- construction ---------------------------------------------------------
    def _decompose(self, selector, scope: Scope) -> None:
        from ..query_api.expression import AttributeFunction as Function
        sel_list = selector.selection_list
        if not sel_list:
            raise CompileError("aggregation needs an explicit select list")
        for oa in sel_list:
            e = oa.expression
            name = oa.rename or (
                e.attribute_name if isinstance(e, Variable) else None)
            if name is None:
                raise CompileError(
                    "aggregation outputs need names (use `as`)")
            if isinstance(e, Variable):
                if e.attribute_name not in self.group_names:
                    raise CompileError(
                        f"aggregation projection {e.attribute_name!r} must "
                        f"be a group-by attribute or an aggregate")
                gpos = self.group_names.index(e.attribute_name)
                self.outputs.append(_Output(
                    name, self.group_types[gpos], "group", (), gpos))
                continue
            if not isinstance(e, Function) or e.namespace:
                raise CompileError(
                    "aggregation selections must be group attrs or "
                    "sum/count/min/max/avg aggregates")
            fn = e.name
            if fn == "count":
                i = self._add_base("count", None, None)
                self.outputs.append(_Output(name, "LONG", "count", (i,)))
                continue
            if fn not in ("sum", "avg", "min", "max"):
                raise CompileError(
                    f"aggregator {fn!r} not supported in incremental "
                    f"aggregations (reference supports "
                    f"sum/count/avg/min/max/distinctCount)")
            if len(e.parameters) != 1:
                raise CompileError(f"{fn}() takes one argument")
            c = compile_expression(e.parameters[0], scope)
            if c.type not in ("INT", "LONG", "FLOAT", "DOUBLE"):
                raise CompileError(f"{fn}() needs a numeric argument")
            is_int = c.type in ("INT", "LONG")
            if fn == "sum":
                i = self._add_base("sum", c.fn, c.type)
                self.outputs.append(_Output(
                    name, "LONG" if is_int else "DOUBLE", "sum", (i,)))
            elif fn in ("min", "max"):
                i = self._add_base(fn, c.fn, c.type)
                self.outputs.append(_Output(name, c.type, fn, (i,)))
            else:  # avg -> sum + count (reference: Avg...Aggregator :57-95)
                si = self._add_base("sum", c.fn, c.type)
                ci = self._add_base("count", None, None)
                self.outputs.append(_Output(name, "DOUBLE", "avg", (si, ci)))

    def _add_base(self, kind: str, value_fn, value_type) -> int:
        # reuse identical base aggs (avg+sum of same expr share the sum)
        key = (kind, id(value_fn) if value_fn else None)
        for i, b in enumerate(self.base):
            if b.kind == kind and b.value_fn is value_fn:
                return i
        self.base.append(_BaseAgg(kind, value_fn, value_type))
        return len(self.base) - 1

    # -- ingestion ------------------------------------------------------------
    def process_staged(self, staged: ev.StagedBatch, now: int) -> None:
        batch = staged.to_device(self.in_schema)
        keep, vals = self._step(
            batch.ts, batch.kind, batch.valid, batch.cols,
            jnp.asarray(now, jnp.int64))
        keep = np.asarray(keep)
        if not keep.any():
            return
        vals = np.asarray(vals)          # [n_base, B]
        ts = (staged.cols[self.ts_pos].astype(np.int64)
              if self.ts_pos >= 0 else staged.ts)
        gcols = [staged.cols[p] for p in self.group_positions]

        idx = np.nonzero(keep)[0]
        ts = ts[idx]
        vals = vals[:, idx]
        gcols = [c[idx] for c in gcols]

        with self._lock:
            for dur in self.durations:
                self._merge_duration(dur, ts, gcols, vals)

    @staticmethod
    def _bits(col: np.ndarray) -> np.ndarray:
        """Lossless int64 encoding of a key column (floats via bit view)."""
        if col.dtype in (np.float32, np.float64):
            return col.astype(np.float64).view(np.int64)
        return col.astype(np.int64)

    def _merge_duration(self, dur: str, ts, gcols, vals) -> None:
        buckets = truncate_buckets(ts, dur)
        # dense (group..., bucket) segmenting
        key_cols = [self._bits(c) for c in gcols] + [buckets]
        stacked = np.stack(key_cols)
        uniq, inv = np.unique(stacked, axis=1, return_inverse=True)
        n = uniq.shape[1]
        store = self.stores[dur]
        partial = np.empty((len(self.base), n))
        for bi, b in enumerate(self.base):
            acc = np.full((n,), b.identity())
            b.np_reduce_at(acc, inv, vals[bi])
            partial[bi] = acc
        for j in range(n):
            key = tuple(int(uniq[ci, j]) for ci in range(len(key_cols)))
            old = store.get(key)
            if old is None:
                store[key] = partial[:, j].copy()
            else:
                store[key] = np.array([
                    b.merge(old[bi], partial[bi, j])
                    for bi, b in enumerate(self.base)])

    # -- reads ----------------------------------------------------------------
    @property
    def out_names(self) -> List[str]:
        return ["AGG_TIMESTAMP"] + [o.name for o in self.outputs]

    @property
    def out_types(self) -> List[str]:
        return ["LONG"] + [o.type for o in self.outputs]

    def make_schema(self) -> ev.Schema:
        from ..query_api.definition import StreamDefinition
        sdef = StreamDefinition(self.definition.id)
        for n, t in zip(self.out_names, self.out_types):
            sdef.attribute(n, t)
        return ev.Schema(sdef, self.app.interner)

    def snapshot_rows(self, per: str, within: Optional[Tuple[int, int]]
                      ) -> Tuple[np.ndarray, List[np.ndarray]]:
        """Materialize (bucket_ts[n], out_cols) for duration `per` within
        the [start, end) range (reference: AggregationRuntime.find +
        IncrementalDataAggregator combining table + running values)."""
        per = normalize_duration(per)
        if per not in self.stores:
            raise CompileError(
                f"aggregation {self.definition.id!r} has no duration "
                f"{per!r}; declared: {self.durations}")
        with self._lock:
            items = list(self.stores[per].items())
        if within is not None:
            s, e = within
            items = [(k, v) for k, v in items if s <= k[-1] < e]
        n = len(items)
        ng = len(self.group_positions)
        ts = np.array([k[-1] for k, _ in items], np.int64) if n else \
            np.zeros((0,), np.int64)
        base = np.stack([v for _, v in items]) if n else \
            np.zeros((0, len(self.base)))
        gkeys = [np.array([k[gi] for k, _ in items], np.int64) if n else
                 np.zeros((0,), np.int64) for gi in range(ng)]
        cols: List[np.ndarray] = [ts]
        for o in self.outputs:
            if o.kind == "group":
                bits = gkeys[o.group_pos]
                if o.type in ("FLOAT", "DOUBLE"):
                    cols.append(bits.view(np.float64).astype(
                        ev.np_dtype(o.type)))
                else:
                    cols.append(bits.astype(ev.np_dtype(o.type)))
            else:
                cols.append(o.finalize(base).astype(ev.np_dtype(o.type)))
        return ts, cols
