"""Incremental time-granularity aggregation.

Reference behavior (what): CORE/aggregation/AggregationRuntime.java:81,
IncrementalExecutor.java:48 (execute :102-130), AggregationParser.java —
`define aggregation A from S select g, avg(x) as ax ... group by g
aggregate by ts every sec...year` maintains running aggregates per duration
bucket (seconds..years); avg decomposes into sum+count base attributes
(incremental/AvgIncrementalAttributeAggregator.java:57-95); queries join
against a duration's buckets `within` a time range (`per "days"`).
Out-of-order events (OutOfOrderEventsDataAggregator.java:177), bucket
purging (IncrementalDataPurger.java:307), restart rebuild from backing
tables (IncrementalExecutorsInitialiser.java:203) and distributed shardId
mode (AggregationParser.java:173-197) are part of the surface.

TPU-native design (how): the reference cascades one executor per duration,
rolling finer buckets into coarser on rollover, which is why it needs
special out-of-order handling (only the current bucket is live in memory).
Here each duration keeps a DEVICE-RESIDENT slab [n_base, capacity] of
running base values; (group-key, bucket-start) pairs resolve to slab slots
through the native SlotAllocator staging path and the per-event merge is a
single jitted scatter (`.at[idx].add/min/max`) on device — no cascade, no
per-bucket dicts, and any bucket (past or present) is updatable, so
out-of-order arrival is the normal path, not a special case.  Purging
frees slots back to the allocator and resets slab columns to the identity.
With a @store annotation the slabs write through to per-duration record
tables (rows tagged with the configured shardId); on start the slabs
rebuild by merging table rows across every shard.  Join and on-demand
reads materialize a padded columnar snapshot (AGG_TIMESTAMP + declared
outputs) that drops into the existing table-join device path.
"""
from __future__ import annotations

import calendar
import datetime
import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..query_api.expression import Constant, Variable
from . import event as ev
from .executor import CompileError, Scope, compile_expression
from .steputil import jit_step

DURATION_MS = {
    "SECONDS": 1000,
    "MINUTES": 60_000,
    "HOURS": 3_600_000,
    "DAYS": 86_400_000,
    # MONTHS / YEARS are calendar-based; handled specially
}

_DUR_ALIASES = {
    "sec": "SECONDS", "second": "SECONDS", "seconds": "SECONDS",
    "min": "MINUTES", "minute": "MINUTES", "minutes": "MINUTES",
    "hour": "HOURS", "hours": "HOURS",
    "day": "DAYS", "days": "DAYS",
    "month": "MONTHS", "months": "MONTHS",
    "year": "YEARS", "years": "YEARS",
}


def normalize_duration(name: str) -> str:
    d = _DUR_ALIASES.get(name.strip().lower())
    if d is None:
        raise CompileError(f"unknown aggregation duration {name!r}")
    return d


def truncate_buckets(ts_ms: np.ndarray, duration: str) -> np.ndarray:
    """Bucket start per timestamp (vectorized; calendar months/years via
    per-unique conversion, matching the reference's calendar semantics —
    IncrementalUnixTimeFunctionUtil)."""
    if duration in DURATION_MS:
        d = DURATION_MS[duration]
        return (ts_ms // d) * d
    uniq, inv = np.unique(ts_ms, return_inverse=True)
    outs = np.empty_like(uniq)
    for i, t in enumerate(uniq):
        dt = datetime.datetime.fromtimestamp(t / 1000.0, datetime.timezone.utc)
        if duration == "MONTHS":
            dt = dt.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        else:  # YEARS
            dt = dt.replace(month=1, day=1, hour=0, minute=0, second=0,
                            microsecond=0)
        outs[i] = int(calendar.timegm(dt.timetuple()) * 1000)
    return outs[inv]


_DATE_FIELDS = ("year", "month", "day", "hour", "minute", "second")


def _parse_date_string(s: str) -> Tuple[int, Optional[str]]:
    """Parse `yyyy-MM-dd HH:mm:ss` (components optional from the right, or
    `**` wildcards) -> (epoch_ms_start, wildcard_field | None).
    Reference: within-clause time formats, aggregation docs."""
    s = s.strip()
    import re
    m = re.match(
        r"^(\d{4}|\*\*)(?:-(\d{1,2}|\*\*))?(?:-(\d{1,2}|\*\*))?"
        r"(?:[ T](\d{1,2}|\*\*))?(?::(\d{1,2}|\*\*))?(?::(\d{1,2}|\*\*))?",
        s)
    if not m or m.group(1) == "**":
        raise CompileError(f"cannot parse within-time {s!r}")
    vals = []
    wildcard = None
    for i, g in enumerate(m.groups()):
        if g is None or g == "**":
            if wildcard is None:
                wildcard = _DATE_FIELDS[i]
            vals.append(None)
        else:
            if wildcard is not None:
                raise CompileError(
                    f"non-wildcard after wildcard in {s!r}")
            vals.append(int(g))
    y = vals[0]
    dt = datetime.datetime(
        y, vals[1] or 1, vals[2] or 1, vals[3] or 0, vals[4] or 0,
        vals[5] or 0)
    return int(calendar.timegm(dt.timetuple()) * 1000), wildcard


def _advance(dt_ms: int, field: str) -> int:
    dt = datetime.datetime.fromtimestamp(dt_ms / 1000.0, datetime.timezone.utc)
    if field == "year":
        dt = dt.replace(year=dt.year + 1)
    elif field == "month":
        dt = dt.replace(year=dt.year + (dt.month == 12),
                        month=dt.month % 12 + 1)
    else:
        delta = {"day": 86_400, "hour": 3_600, "minute": 60, "second": 1}
        return dt_ms + delta[field] * 1000
    return int(calendar.timegm(dt.timetuple()) * 1000)


def _bound_of(expr) -> Tuple[int, Optional[str]]:
    if isinstance(expr, Constant):
        if expr.type in ("LONG", "INT"):
            return int(expr.value), None
        if expr.type == "STRING":
            return _parse_date_string(str(expr.value))
    raise CompileError(
        "within bounds must be time-string or epoch-ms constants")


def parse_within(within) -> Tuple[int, int]:
    """within '2020-01-01 ...' [, '2020-02-01 ...'] -> [start, end) ms."""
    if within is None:
        raise CompileError(
            "aggregation reads need a `within` clause (reference: "
            "AggregationRuntime.compileExpression)")
    if isinstance(within, tuple):
        s, _ = _bound_of(within[0])
        e, _ = _bound_of(within[1])
        return s, e
    s, wildcard = _bound_of(within)
    if wildcard is None:
        # single full timestamp: that instant's smallest covered unit
        return s, _advance(s, "second")
    return s, _advance(s, {"month": "year", "day": "month",
                           "hour": "day", "minute": "hour",
                           "second": "minute"}[wildcard])


def parse_per(per) -> str:
    if per is None:
        raise CompileError("aggregation reads need a `per` duration")
    if isinstance(per, Constant) and per.type == "STRING":
        return normalize_duration(str(per.value))
    if isinstance(per, Variable):
        return normalize_duration(per.attribute_name)
    raise CompileError("per must be a duration name")


class _BaseAgg:
    """One base (decomposed) aggregation: a compiled value expression and a
    merge rule."""

    def __init__(self, kind: str, value_fn, dtype):
        self.kind = kind          # 'sum' | 'count' | 'min' | 'max'
        self.value_fn = value_fn  # env -> [B] values (None for count)
        self.dtype = dtype

    def identity(self) -> float:
        if self.kind == "min":
            return np.inf
        if self.kind == "max":
            return -np.inf
        return 0.0

    def merge(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        if self.kind == "min":
            return np.minimum(a, b)
        if self.kind == "max":
            return np.maximum(a, b)
        return a + b

    def np_reduce_at(self, acc: np.ndarray, idx: np.ndarray,
                     vals: np.ndarray) -> None:
        if self.kind == "min":
            np.minimum.at(acc, idx, vals)
        elif self.kind == "max":
            np.maximum.at(acc, idx, vals)
        else:
            np.add.at(acc, idx, vals)


# reference retention defaults (IncrementalDataPurger.java:307 /
# aggregation docs); None = keep forever ("all")
_DEFAULT_RETENTION_MS = {
    "SECONDS": 120_000,
    "MINUTES": 24 * 3_600_000,
    "HOURS": 30 * 86_400_000,
    "DAYS": 366 * 86_400_000,
    "MONTHS": None,
    "YEARS": None,
}

_TIME_UNITS_MS = {
    "ms": 1, "millisec": 1, "millisecond": 1, "milliseconds": 1,
    "sec": 1000, "second": 1000, "seconds": 1000,
    "week": 7 * 86_400_000, "weeks": 7 * 86_400_000,
    "min": 60_000, "minute": 60_000, "minutes": 60_000,
    "hour": 3_600_000, "hours": 3_600_000,
    "day": 86_400_000, "days": 86_400_000,
    "month": 30 * 86_400_000, "months": 30 * 86_400_000,
    "year": 365 * 86_400_000, "years": 365 * 86_400_000,
}


def parse_time_ms(s: str) -> Optional[int]:
    """'120 sec' / '24 hours' / 'all' -> milliseconds (None = unbounded)."""
    s = str(s).strip().lower()
    if s == "all":
        return None
    parts = s.split()
    if len(parts) == 2 and parts[1] in _TIME_UNITS_MS:
        return int(float(parts[0]) * _TIME_UNITS_MS[parts[1]])
    if s.isdigit():
        return int(s)
    raise CompileError(f"cannot parse time value {s!r}")


class _DurationStore:
    """Device-resident bucket slab for one duration: running base values
    [n_base, capacity] indexed by slot, with (group-bits..., bucket) keys
    resolved through the native SlotAllocator (reference role: the
    per-duration BaseIncrementalValueStore maps + backing table)."""

    def __init__(self, agg_name: str, dur: str, identities: np.ndarray,
                 capacity: int, mesh=None):
        from .keyslots import SlotAllocator
        self.dur = dur
        self.capacity = capacity
        self.alloc = SlotAllocator(capacity, f"{agg_name}:{dur}")
        self.identities = identities                    # [n_base] f64
        self.mesh = mesh
        self.slab = self.place(jnp.asarray(
            np.tile(identities[:, None], (1, capacity))))
        # slots written since the last table flush (@store write-through)
        self.dirty = np.zeros(capacity, np.bool_)
        # slots written since the last (incremental) snapshot baseline
        self.snap_dirty = np.zeros(capacity, np.bool_)

    def place(self, slab):
        """Bucket axis shards over the mesh (GSPMD: the jitted scatter-
        merge auto-partitions; replicated indices route to shard owners).
        Scale-out story for aggregation state — reference's equivalent is
        the shardId multi-JVM store split (AggregationParser :173-197)."""
        if self.mesh is None:
            return slab
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(slab, NamedSharding(self.mesh,
                                                  P(None, "shard")))

    def decode_keys(self) -> Tuple[np.ndarray, np.ndarray]:
        """(slots [n], key_words [n, ng+1] int64) for live slots."""
        mapping = self.alloc.snapshot()
        n = len(mapping)
        if n == 0:
            return np.zeros((0,), np.int64), np.zeros((0, 1), np.int64)
        slots = np.fromiter(mapping.values(), np.int64, n)
        words = np.frombuffer(b"".join(mapping.keys()), np.int64)
        return slots, words.reshape(n, -1)

    def reset_slots(self, slots: np.ndarray) -> None:
        if not len(slots):
            return
        if self.mesh is None:
            self.slab = self.slab.at[:, jnp.asarray(slots)].set(
                jnp.asarray(self.identities)[:, None])
        else:
            # host-context scatters into a sharded slab drop remote-shard
            # updates: go through the shared masked-where helper
            from .shardsafe import key_mask, masked_fill
            self.slab = masked_fill(
                self.slab, key_mask(slots, self.capacity),
                jnp.asarray(self.identities)[:, None], key_axis=1)
        self.dirty[slots] = False

    def scatter_rows(self, slots: np.ndarray, rows_t: np.ndarray) -> None:
        """Write rows_t [n_base, n] into slab columns `slots` (restore
        paths)."""
        if not len(slots):
            return
        if self.mesh is None:   # sparse fast path (no dense temp)
            self.slab = self.slab.at[:, jnp.asarray(slots)].set(
                jnp.asarray(rows_t))
            return
        from .shardsafe import key_mask, masked_fill
        upd = np.zeros((rows_t.shape[0], self.capacity), np.float64)
        upd[:, slots] = rows_t
        self.slab = masked_fill(
            self.slab, key_mask(slots, self.capacity), jnp.asarray(upd),
            key_axis=1)


def _null_of(attr_type: str) -> float:
    """The output type's in-band null as float64 (int sentinels are exact
    in f64 for the reserved minima)."""
    v = ev.null_value(attr_type)
    return float(v)


class _Output:
    """One declared output attribute and how to finalize it from base
    values (reference: IncrementalAttributeAggregator SPI)."""

    def __init__(self, name: str, attr_type: str, kind: str,
                 base_idx: Tuple[int, ...], group_pos: int = -1,
                 custom_fn=None):
        self.name = name
        self.type = attr_type
        self.kind = kind  # 'group'|'sum'|'count'|'min'|'max'|'avg'|'custom'
        self.base_idx = base_idx
        self.group_pos = group_pos  # index into group key tuple for 'group'
        self.custom_fn = custom_fn  # custom SPI: fn([cols]) -> col

    def finalize(self, base: np.ndarray) -> np.ndarray:
        """base: [n_rows, n_base] -> [n_rows] output column.  A bucket
        whose inputs were ALL null yields null (the in-band value of the
        output type — NaN would crash int decode for LONG sums)."""
        nullv = float(_null_of(self.type))
        if self.kind == "avg":
            s, c = base[:, self.base_idx[0]], base[:, self.base_idx[1]]
            return np.where(c > 0, s / np.maximum(c, 1), nullv)
        if self.kind == "custom":
            return np.asarray(self.custom_fn(
                [base[:, i] for i in self.base_idx]))
        col = base[:, self.base_idx[0]]
        if self.kind in ("sum", "min", "max") and len(self.base_idx) > 1:
            # the paired non-null count decides emptiness — sniffing the
            # accumulator for its identity would misread legitimate ±inf
            # data as an empty bucket
            return np.where(base[:, self.base_idx[1]] > 0, col, nullv)
        return col


class AggregationRuntime:
    """Host+device runtime for one `define aggregation`."""

    def __init__(self, adef, app):
        self.definition = adef
        self.app = app
        sis = adef.basic_single_input_stream
        self.input_stream_id = sis.unique_stream_id
        schema = app.schemas.get(self.input_stream_id)
        if schema is None:
            raise CompileError(
                f"aggregation {adef.id!r}: undefined stream "
                f"{self.input_stream_id!r}")
        self.in_schema = schema
        self._lock = threading.RLock()

        scope = Scope()
        scope.interner = app.interner
        scope.add_source(self.input_stream_id, schema,
                         alias=sis.stream_reference_id)

        # filters on the input stream
        from ..query_api.query import Filter
        self._filters = []
        for h in sis.stream_handlers:
            if isinstance(h, Filter):
                c = compile_expression(h.expression, scope)
                if c.type != "BOOL":
                    raise CompileError("aggregation filter must be boolean")
                self._filters.append(c)
            else:
                raise CompileError(
                    "aggregation input supports filters only")

        # group-by columns
        self.group_names = [v.attribute_name
                            for v in (adef.selector.group_by_list or [])]
        self.group_positions = [schema.position(n) for n in self.group_names]
        self.group_types = [schema.types[p] for p in self.group_positions]

        # aggregate-by timestamp attribute (or event ts)
        self.ts_pos = -1
        if adef.aggregate_attribute is not None:
            self.ts_pos = schema.position(
                adef.aggregate_attribute.attribute_name)

        # decompose selection into base aggregations + outputs
        self.base: List[_BaseAgg] = []
        self.outputs: List[_Output] = []
        self._decompose(adef.selector, scope)

        self.durations = [normalize_duration(d) for d in adef.time_periods] \
            or ["SECONDS"]
        self._identities = np.array([b.identity() for b in self.base],
                                    np.float64)
        cap_ann = adef.get_annotation("capacity") if \
            hasattr(adef, "get_annotation") else None
        self.bucket_capacity = int(cap_ann.element("buckets")) \
            if cap_ann is not None and cap_ann.element("buckets") else 1 << 16
        from ..sharding import shard_count
        agg_mesh = app.mesh
        if agg_mesh is not None and (
                shard_count(agg_mesh) < 2 or
                self.bucket_capacity % shard_count(agg_mesh) != 0):
            agg_mesh = None
        self._dstores: Dict[str, _DurationStore] = {
            d: _DurationStore(adef.id, d, self._identities,
                              self.bucket_capacity, mesh=agg_mesh)
            for d in self.durations}

        # retention per duration: defaults from the reference, overridable
        # with @retentionPeriod(sec='120 sec', min='24 hours', ..., or 'all')
        self.retention_ms: Dict[str, Optional[int]] = {
            d: _DEFAULT_RETENTION_MS[d] for d in self.durations}
        ret_ann = adef.get_annotation("retentionPeriod") if \
            hasattr(adef, "get_annotation") else None
        if ret_ann is not None:
            alias = {"sec": "SECONDS", "min": "MINUTES", "hours": "HOURS",
                     "days": "DAYS", "months": "MONTHS", "years": "YEARS"}
            for k, dur in alias.items():
                v = ret_ann.element(k)
                if v is not None and dur in self.retention_ms:
                    self.retention_ms[dur] = parse_time_ms(v)
        # @purge(enable='true'|'false', interval='10 sec')
        purge_ann = adef.get_annotation("purge") if \
            hasattr(adef, "get_annotation") else None
        self.purge_enabled = True
        self.purge_interval_ms = 15_000
        if purge_ann is not None:
            if purge_ann.element("enable") is not None:
                self.purge_enabled = str(
                    purge_ann.element("enable")).lower() == "true"
            if purge_ann.element("interval") is not None:
                iv = parse_time_ms(purge_ann.element("interval"))
                if not iv or iv <= 0:
                    raise CompileError(
                        f"@purge interval must be a positive time value, "
                        f"got {purge_ann.element('interval')!r}")
                self.purge_interval_ms = iv

        # distributed mode: rows written to the backing store are tagged
        # with this process's shardId; reads merge across shards
        # (reference: AggregationParser :173-197, shardId system config)
        sysconf = {}
        if getattr(app, "config_manager", None) is not None:
            try:
                sysconf = app.config_manager.extract_system_configs() or {}
            except Exception:   # noqa: BLE001 — config is best-effort
                sysconf = {}
        self.shard_id = str(sysconf.get("shardId", ""))
        self._store_tables: Dict[str, object] = {}
        store_ann = adef.get_annotation("store") if \
            hasattr(adef, "get_annotation") else None
        if store_ann is not None:
            self._init_store_tables(store_ann)

        # device step: batch -> (valid mask, stacked base values)
        filters = self._filters
        base = self.base
        sid = self.input_stream_id

        def step(ts, kind, valid, cols, now):
            env = {sid: cols, "__ts__": ts, "__now__": now}
            keep = jnp.logical_and(valid, kind == ev.CURRENT)
            for f in filters:
                keep = jnp.logical_and(keep, f.fn(env))
            vals = []
            for b in base:
                if b.value_fn is None:
                    vals.append(jnp.ones(ts.shape, jnp.float64))
                    continue
                raw = b.value_fn(env)
                v = jnp.asarray(raw, jnp.float64)
                if b.dtype is not None:
                    # null inputs contribute the accumulator identity —
                    # one NaN would otherwise poison its bucket FOREVER
                    # (reference: incremental aggregators skip nulls)
                    v = jnp.where(ev.null_mask(raw, b.dtype),
                                  jnp.asarray(b.identity(), jnp.float64), v)
                vals.append(v)
            return keep, jnp.stack(vals) if vals else jnp.zeros((0,) + ts.shape)

        self._step = jit_step(step, owner=f"agg:{adef.id}")

        # device merge: one scatter per base row into the duration slab
        kinds = tuple(b.kind for b in self.base)
        cap = self.bucket_capacity

        def merge(slab, idx, vals):
            # idx: [B] int32, -1 (invalid) mapped out-of-bounds -> dropped
            ii = jnp.where(idx >= 0, idx, cap)
            rows = []
            for bi, k in enumerate(kinds):
                r = slab[bi]
                if k == "min":
                    r = r.at[ii].min(vals[bi], mode="drop")
                elif k == "max":
                    r = r.at[ii].max(vals[bi], mode="drop")
                else:
                    r = r.at[ii].add(vals[bi], mode="drop")
                rows.append(r)
            return jnp.stack(rows)

        self._merge = jit_step(merge, owner=f"agg:{adef.id}",
                               donate_argnums=(0,))

    # -- construction ---------------------------------------------------------
    def _decompose(self, selector, scope: Scope) -> None:
        from ..query_api.expression import AttributeFunction as Function
        sel_list = selector.selection_list
        if not sel_list:
            raise CompileError("aggregation needs an explicit select list")
        for oa in sel_list:
            e = oa.expression
            name = oa.rename or (
                e.attribute_name if isinstance(e, Variable) else None)
            if name is None:
                raise CompileError(
                    "aggregation outputs need names (use `as`)")
            if isinstance(e, Variable):
                if e.attribute_name not in self.group_names:
                    raise CompileError(
                        f"aggregation projection {e.attribute_name!r} must "
                        f"be a group-by attribute or an aggregate")
                gpos = self.group_names.index(e.attribute_name)
                self.outputs.append(_Output(
                    name, self.group_types[gpos], "group", (), gpos))
                continue
            if not isinstance(e, Function):
                raise CompileError(
                    "aggregation selections must be group attrs or "
                    "sum/count/min/max/avg aggregates")
            if e.namespace:
                # custom incremental aggregator (reference:
                # IncrementalAttributeAggregator SPI resolved through
                # IncrementalAttributeAggregatorExtensionHolder): it
                # DECLARES base sum/count/min/max accumulators and a
                # finalize over their running values — same decomposition
                # contract the built-in avg uses
                from .extension import incremental_aggregator_registry
                full = f"{e.namespace}:{e.name}"
                ext_cls = incremental_aggregator_registry().get(full)
                if ext_cls is None:
                    raise CompileError(
                        f"unknown incremental aggregator {full!r}; "
                        f"registered: "
                        f"{sorted(incremental_aggregator_registry())}")
                args_c = [compile_expression(p, scope)
                          for p in e.parameters]
                inst = ext_cls()
                idxs, fin = inst.decompose(args_c, self._add_base)
                self.outputs.append(_Output(
                    name, inst.return_type.upper(), "custom",
                    tuple(idxs), custom_fn=fin))
                continue
            fn = e.name
            if fn == "count":
                i = self._add_base("count", None, None)
                self.outputs.append(_Output(name, "LONG", "count", (i,)))
                continue
            if fn not in ("sum", "avg", "min", "max"):
                raise CompileError(
                    f"aggregator {fn!r} not supported in incremental "
                    f"aggregations (reference supports "
                    f"sum/count/avg/min/max/distinctCount)")
            if len(e.parameters) != 1:
                raise CompileError(f"{fn}() takes one argument")
            # ONE CompiledExpr per distinct argument expression: this is
            # what lets _add_base's identity dedup and _count_nonnull's
            # memo actually share slab rows across sum/avg/min/max of the
            # same expr
            from .selector import _expr_fingerprint
            if not hasattr(self, "_arg_cache"):
                self._arg_cache = {}
            akey = _expr_fingerprint(e.parameters[0])
            c = self._arg_cache.get(akey)
            if c is None:
                c = compile_expression(e.parameters[0], scope)
                self._arg_cache[akey] = c
            if c.type not in ("INT", "LONG", "FLOAT", "DOUBLE"):
                raise CompileError(f"{fn}() needs a numeric argument")
            is_int = c.type in ("INT", "LONG")
            if fn == "sum":
                i = self._add_base("sum", c.fn, c.type)
                ci = self._add_base("count", self._count_nonnull(c), None)
                self.outputs.append(_Output(
                    name, "LONG" if is_int else "DOUBLE", "sum", (i, ci)))
            elif fn in ("min", "max"):
                i = self._add_base(fn, c.fn, c.type)
                ci = self._add_base("count", self._count_nonnull(c), None)
                self.outputs.append(_Output(name, c.type, fn, (i, ci)))
            else:  # avg -> sum + count (reference: Avg...Aggregator :57-95)
                si = self._add_base("sum", c.fn, c.type)
                # nulls count for neither the sum nor the divisor
                ci = self._add_base("count", self._count_nonnull(c), None)
                self.outputs.append(_Output(name, "DOUBLE", "avg", (si, ci)))

    def _count_nonnull(self, c):
        """Shared per-argument non-null counter base fn (sum+avg of one
        expr share a single scatter row)."""
        if not hasattr(self, "_cnt_fns"):
            self._cnt_fns = {}
        fn = self._cnt_fns.get(id(c))
        if fn is None:
            def fn(env, _c=c):
                v = _c.fn(env)
                return jnp.where(ev.null_mask(v, _c.type), 0.0, 1.0)
            self._cnt_fns[id(c)] = fn
        return fn

    def _add_base(self, kind: str, value_fn, value_type) -> int:
        # also the custom IncrementalAttributeAggregator SPI's entry: an
        # unknown kind would silently fall through to the additive merge
        if kind not in ("sum", "count", "min", "max"):
            raise CompileError(
                f"incremental base accumulator kind {kind!r} is not one of "
                f"sum/count/min/max")
        # reuse identical base aggs (avg+sum of same expr share the sum)
        key = (kind, id(value_fn) if value_fn else None)
        for i, b in enumerate(self.base):
            if b.kind == kind and b.value_fn is value_fn:
                return i
        self.base.append(_BaseAgg(kind, value_fn, value_type))
        return len(self.base) - 1

    # -- ingestion ------------------------------------------------------------
    def process_staged(self, staged: ev.StagedBatch, now: int) -> None:
        """Merge a batch into every duration slab.  Any bucket (past or
        future) is addressable, so out-of-order events need no special
        path (reference: OutOfOrderEventsDataAggregator.java:177)."""
        batch = staged.to_device(self.in_schema)
        keep_d, vals_d = self._step(
            batch.ts, batch.kind, batch.valid, batch.cols,
            jnp.asarray(now, jnp.int64))
        keep = np.asarray(keep_d)
        if not keep.any():
            return
        ts = (staged.cols[self.ts_pos].astype(np.int64)
              if self.ts_pos >= 0 else staged.ts)
        gcols = [staged.cols[p] for p in self.group_positions]

        with self._lock:
            for dur in self.durations:
                ds = self._dstores[dur]
                buckets = truncate_buckets(ts, dur)
                key_cols = [self._bits(c) for c in gcols] + [buckets]
                slots = ds.alloc.slots_for(key_cols, valid=keep)
                ds.slab = self._merge(ds.slab, jnp.asarray(slots), vals_d)
                live = slots[slots >= 0]
                if live.size:
                    ds.dirty[live] = True
                    ds.snap_dirty[live] = True

    @staticmethod
    def _bits(col: np.ndarray) -> np.ndarray:
        """Lossless int64 encoding of a key column (floats via bit view)."""
        if col.dtype in (np.float32, np.float64):
            return col.astype(np.float64).view(np.int64)
        return col.astype(np.int64)

    # -- purging (reference: IncrementalDataPurger.java:307) ------------------
    def on_timer(self, now: int) -> None:
        if self.purge_enabled:
            self.purge_old(now)
        if self._store_tables:
            self.flush_to_store()
        self.app._scheduler.notify_at(now + self.purge_interval_ms, self)

    def purge_old(self, now: int) -> None:
        """Free buckets past their duration's retention period; their slots
        recycle through the allocator free list."""
        with self._lock:
            for dur in self.durations:
                ret = self.retention_ms.get(dur)
                if ret is None:
                    continue
                ds = self._dstores[dur]
                slots, words = ds.decode_keys()
                if not len(slots):
                    continue
                old = words[:, -1] < (now - ret)
                if old.any():
                    # store rows for purged buckets vanish at the next
                    # flush (flush_to_store rewrites this shard wholesale)
                    doomed = slots[old]
                    ds.alloc.purge(doomed.tolist())
                    ds.reset_slots(doomed)
                    ds.dirty[doomed] = True     # force a table rewrite

    # -- reads ----------------------------------------------------------------
    @property
    def out_names(self) -> List[str]:
        return ["AGG_TIMESTAMP"] + [o.name for o in self.outputs]

    @property
    def out_types(self) -> List[str]:
        return ["LONG"] + [o.type for o in self.outputs]

    def make_schema(self) -> ev.Schema:
        from ..query_api.definition import StreamDefinition
        sdef = StreamDefinition(self.definition.id)
        for n, t in zip(self.out_names, self.out_types):
            sdef.attribute(n, t)
        return ev.Schema(sdef, self.app.interner)

    def _local_rows(self, per: str) -> Tuple[np.ndarray, np.ndarray]:
        """(keys [n, ng+1] int64 — group bits then bucket, base [n, n_base])
        from this process's device slab."""
        ds = self._dstores[per]
        with self._lock:
            slots, words = ds.decode_keys()
            slab = np.asarray(ds.slab)
        if not len(slots):
            return (np.zeros((0, len(self.group_positions) + 1), np.int64),
                    np.zeros((0, len(self.base))))
        return words, slab[:, slots].T

    def snapshot_rows(self, per: str, within: Optional[Tuple[int, int]]
                      ) -> Tuple[np.ndarray, List[np.ndarray]]:
        """Materialize (bucket_ts[n], out_cols) for duration `per` within
        the [start, end) range (reference: AggregationRuntime.find +
        IncrementalDataAggregator combining table + running values).  In
        distributed mode rows from OTHER shards merge in from the backing
        table (reference: shardId reads, AggregationParser :464-470)."""
        per = normalize_duration(per)
        if per not in self._dstores:
            raise CompileError(
                f"aggregation {self.definition.id!r} has no duration "
                f"{per!r}; declared: {self.durations}")
        keys, base = self._local_rows(per)
        if self._store_tables:
            okeys, obase = self._other_shard_rows(per)
            if len(okeys):
                keys, base = self._merge_rows(
                    np.concatenate([keys, okeys]),
                    np.concatenate([base, obase]))
        if within is not None:
            s, e = within
            m = (keys[:, -1] >= s) & (keys[:, -1] < e)
            keys, base = keys[m], base[m]
        ts = keys[:, -1].copy() if len(keys) else np.zeros((0,), np.int64)
        cols: List[np.ndarray] = [ts]
        for o in self.outputs:
            if o.kind == "group":
                bits = keys[:, o.group_pos].copy()
                if o.type in ("FLOAT", "DOUBLE"):
                    cols.append(bits.view(np.float64).astype(
                        ev.np_dtype(o.type)))
                else:
                    cols.append(bits.astype(ev.np_dtype(o.type)))
            else:
                cols.append(o.finalize(base).astype(ev.np_dtype(o.type)))
        return ts, cols

    def _merge_rows(self, keys: np.ndarray, base: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray]:
        """Merge duplicate (group..., bucket) rows with each base's rule."""
        uniq, inv = np.unique(keys, axis=0, return_inverse=True)
        out = np.tile(self._identities, (len(uniq), 1))
        for bi, b in enumerate(self.base):
            b.np_reduce_at(out[:, bi], inv, base[:, bi])
        return uniq, out

    # -- snapshot compatibility (runtime.snapshot reads/writes `stores`) ------
    @property
    def stores(self) -> Dict[str, Dict[tuple, np.ndarray]]:
        out: Dict[str, Dict[tuple, np.ndarray]] = {}
        for dur in self.durations:
            keys, base = self._local_rows(dur)
            out[dur] = {tuple(int(w) for w in keys[i]): base[i].copy()
                        for i in range(len(keys))}
        return out

    @stores.setter
    def stores(self, value: Dict[str, Dict[tuple, np.ndarray]]) -> None:
        with self._lock:
            for dur in self.durations:
                ds = self._dstores[dur]
                ds.alloc.restore({})
                ds.slab = ds.place(jnp.asarray(
                    np.tile(self._identities[:, None],
                            (1, self.bucket_capacity))))
                ds.dirty[:] = False
                mapping = value.get(dur) or {}
                if not mapping:
                    continue
                keys = np.array(list(mapping.keys()), np.int64)
                rows = np.stack([np.asarray(v, np.float64)
                                 for v in mapping.values()])
                cols = [np.ascontiguousarray(keys[:, i])
                        for i in range(keys.shape[1])]
                slots = ds.alloc.slots_for(cols)
                ds.scatter_rows(slots, rows.T)

    def snapshot_delta(self) -> Dict[str, Dict[tuple, np.ndarray]]:
        """Buckets written since the last snapshot baseline (per duration),
        as absolute rows; resets the baseline.  Keeps incremental persists
        proportional to CHANGE, not slab capacity."""
        out: Dict[str, Dict[tuple, np.ndarray]] = {}
        with self._lock:
            for dur in self.durations:
                ds = self._dstores[dur]
                idx = np.nonzero(ds.snap_dirty)[0]
                if not len(idx):
                    out[dur] = {}
                    continue
                ds.snap_dirty[:] = False
                slots, words = ds.decode_keys()
                live = np.isin(slots, idx)     # dirty AND currently bound
                if not live.any():
                    out[dur] = {}
                    continue
                slab = np.asarray(ds.slab)
                lslots, lwords = slots[live], words[live]
                rows = slab[:, lslots].T
                out[dur] = {tuple(int(x) for x in lwords[i]): rows[i].copy()
                            for i in range(len(lslots))}
        return out

    def apply_delta(self, value: Dict[str, Dict[tuple, np.ndarray]]) -> None:
        """Overwrite the given buckets with rows from an incremental
        snapshot (values are absolute, not diffs)."""
        with self._lock:
            for dur, mapping in (value or {}).items():
                ds = self._dstores.get(dur)
                if ds is None or not mapping:
                    continue
                keys = np.array(list(mapping.keys()), np.int64)
                rows = np.stack([np.asarray(v, np.float64)
                                 for v in mapping.values()])
                cols = [np.ascontiguousarray(keys[:, i])
                        for i in range(keys.shape[1])]
                slots = ds.alloc.slots_for(cols)
                ds.scatter_rows(slots, rows.T)

    def clear_snapshot_baseline(self) -> None:
        with self._lock:
            for ds in self._dstores.values():
                ds.snap_dirty[:] = False

    # -- @store backing tables (reference: AggregationParser table-per-
    #    duration + IncrementalExecutorsInitialiser.java:203) ----------------
    def _store_schema(self):
        from ..query_api.definition import StreamDefinition
        sdef = StreamDefinition(self.definition.id + "_STORE")
        sdef.attribute("SHARD_ID", "STRING")
        sdef.attribute("AGG_TIMESTAMP", "LONG")
        for n, t in zip(self.group_names, self.group_types):
            sdef.attribute(n, t)
        for i in range(len(self.base)):
            sdef.attribute(f"_b{i}", "DOUBLE")
        return sdef

    def _init_store_tables(self, store_ann) -> None:
        from ..io.store import connect_with_retry, create_store
        props = {k: v for k, v in (store_ann.elements or {}).items()
                 if k != "type"}
        sdef = self._store_schema()
        schema = ev.Schema(sdef, self.app.interner)
        for dur in self.durations:
            from ..query_api.definition import TableDefinition
            tdef = TableDefinition(f"{self.definition.id}_{dur}")
            st = create_store(store_ann.element("type"), tdef, schema, props)
            connect_with_retry(st, tdef.id)
            self._store_tables[dur] = st
        self.rebuild_from_store()

    def _row_decoders(self):
        dec = []
        for t in self.group_types:
            if t.upper() == "STRING":
                dec.append(self.app.interner.lookup)
            else:
                dec.append(None)
        return dec

    def flush_to_store(self) -> None:
        """Write this shard's live buckets through to the per-duration
        tables.  Rewrite is wholesale per shard but skipped entirely for
        durations with no writes since the last flush (dirty mask)."""
        dec = self._row_decoders()
        for dur, st in self._store_tables.items():
            ds = self._dstores[dur]
            if not ds.dirty.any():
                continue
            ds.dirty[:] = False
            keys, base = self._local_rows(dur)
            rows = []
            for i in range(len(keys)):
                gvals = []
                for gi, d in enumerate(dec):
                    bits = int(keys[i, gi])
                    if d is not None:
                        gvals.append(d(bits))
                    elif self.group_types[gi].upper() in ("FLOAT", "DOUBLE"):
                        gvals.append(float(
                            np.int64(bits).view(np.float64)))
                    else:
                        gvals.append(bits)
                rows.append(tuple([self.shard_id, int(keys[i, -1])] + gvals +
                                  [float(v) for v in base[i]]))
            stale = [r for r in st.read_all() if r[0] == self.shard_id]
            if stale:
                st.delete_rows(stale)
            if rows:
                st.add(rows)

    def _table_keyed_rows(self, per: str, include_own: bool
                          ) -> Tuple[np.ndarray, np.ndarray]:
        st = self._store_tables.get(per)
        ng = len(self.group_positions)
        if st is None:
            return (np.zeros((0, ng + 1), np.int64),
                    np.zeros((0, len(self.base))))
        keys, base = [], []
        for r in st.read_all():
            if (r[0] == self.shard_id) != include_own:
                continue
            gbits = []
            for gi, t in enumerate(self.group_types):
                v = r[2 + gi]
                tu = t.upper()
                if tu == "STRING":
                    gbits.append(self.app.interner.intern(v))
                elif tu in ("FLOAT", "DOUBLE"):
                    gbits.append(int(np.float64(v).view(np.int64)))
                else:
                    gbits.append(int(v))
            keys.append(gbits + [int(r[1])])
            base.append([float(x) for x in r[2 + ng:2 + ng + len(self.base)]])
        if not keys:
            return (np.zeros((0, ng + 1), np.int64),
                    np.zeros((0, len(self.base))))
        return np.array(keys, np.int64), np.array(base, np.float64)

    def _other_shard_rows(self, per: str):
        return self._table_keyed_rows(per, include_own=False)

    def rebuild_from_store(self) -> None:
        """Recreate this shard's in-memory slabs from its table rows
        (reference: IncrementalExecutorsInitialiser.java:203)."""
        with self._lock:
            for dur in self.durations:
                keys, base = self._table_keyed_rows(dur, include_own=True)
                if not len(keys):
                    continue
                ds = self._dstores[dur]
                cols = [np.ascontiguousarray(keys[:, i])
                        for i in range(keys.shape[1])]
                slots = ds.alloc.slots_for(cols)
                ds.scatter_rows(slots, base.T)
                ds.dirty[slots] = True
