"""Planner + host runtime glue for pattern/sequence queries.

Reference role: CORE/util/parser/StateInputStreamParser.java (NFA build) +
pattern receivers (CORE/query/input/stream/state/receiver/*).  Each pattern
query compiles to one jitted step per input stream; the host groups incoming
events by partition key into a [K, E] layout and the device scan does the
sequential-per-key NFA advance.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..query_api.definition import StreamDefinition
from ..query_api.query import Query, StateInputStream
from . import event as ev
from .executor import CompileError, Scope
from .pattern import PatternExec, PatternSpec, linearize
from .selector import SelectorExec
from .window import NO_WAKEUP, Rows


@dataclasses.dataclass
class PlannedPatternQuery:
    name: str
    spec: PatternSpec
    exec: PatternExec
    in_schemas: Dict[str, ev.Schema]
    out_schema: ev.Schema
    output_target: str
    output_event_type: str
    steps: Dict[str, Callable]          # stream_id -> jitted step
    timer_step: Optional[Callable]
    init_state: Callable                # (K) -> (pattern_state, sel_state)
    key_capacity: int
    slots: int
    partition_positions: Optional[Dict[str, List[int]]] = None


def plan_pattern_query(
    query: Query,
    name: str,
    schemas: Dict[str, ev.Schema],
    interner: ev.StringInterner,
    key_capacity: int = 1,
    slots: int = 8,
    count_cap: int = 8,
    partition_positions: Optional[Dict[str, List[int]]] = None,
) -> PlannedPatternQuery:
    sis = query.input_stream
    assert isinstance(sis, StateInputStream)
    spec = linearize(sis, count_cap=count_cap)
    for sid in spec.stream_ids:
        if sid not in schemas:
            raise CompileError(f"undefined stream {sid!r} in pattern")
    pexec = PatternExec(spec, schemas, interner, slots=slots)

    out_target = query.output_stream.target_id if query.output_stream else ""
    # per-key aggregation: the selector's group slots are the partition keys
    group_slots = key_capacity if partition_positions else 64
    sel = SelectorExec(query.selector, pexec.scope,
                       _first_schema(spec, schemas), group_slots,
                       out_target or name, interner)

    out_def = StreamDefinition(out_target or f"#{name}.out")
    for n, t in zip(sel.out_names, sel.out_types):
        out_def.attribute(n, t)
    out_schema = ev.Schema(out_def, interner)

    P = pexec.P
    refs = [a.ref for a in spec.all_atoms() if not a.absent]
    depths = {a.ref: a.capture_depth for a in spec.all_atoms() if not a.absent}

    def make_step(stream_id: str):
        def step(pstate, sel_state, cols, ts, valid, ord_, key_idx, now):
            # gather this batch's keys ([K_total,...] -> [Kb,...])
            sub = pstate.__class__(
                active=pstate.active[key_idx], pos=pstate.pos[key_idx],
                count=pstate.count[key_idx], lmask=pstate.lmask[key_idx],
                start_ts=pstate.start_ts[key_idx],
                entry_ts=pstate.entry_ts[key_idx],
                seed_on=pstate.seed_on[key_idx], done=pstate.done[key_idx],
                dropped=pstate.dropped,
                caps={k: (v[0][key_idx], tuple(c[key_idx] for c in v[1]))
                      for k, v in pstate.caps.items()})

            def body(carry, xs):
                st = carry
                cols_e, ts_e, valid_e = xs
                now_k = jnp.where(valid_e, ts_e, now)
                st, emit = pexec.tick(st, stream_id, cols_e, ts_e, valid_e,
                                      now_k)
                return st, emit

            xs = (tuple(c.T for c in cols), ts.T, valid.T)   # scan over E
            sub, emits = lax.scan(body, sub, xs)

            # scatter back
            pstate = pstate.__class__(
                active=pstate.active.at[key_idx].set(sub.active),
                pos=pstate.pos.at[key_idx].set(sub.pos),
                count=pstate.count.at[key_idx].set(sub.count),
                lmask=pstate.lmask.at[key_idx].set(sub.lmask),
                start_ts=pstate.start_ts.at[key_idx].set(sub.start_ts),
                entry_ts=pstate.entry_ts.at[key_idx].set(sub.entry_ts),
                seed_on=pstate.seed_on.at[key_idx].set(sub.seed_on),
                done=pstate.done.at[key_idx].set(sub.done),
                dropped=sub.dropped,
                caps={k: (pstate.caps[k][0].at[key_idx].set(v[0]),
                          tuple(pc.at[key_idx].set(c) for pc, c in
                                zip(pstate.caps[k][1], v[1])))
                      for k, v in sub.caps.items()})

            sel_state, out, wake = _emit_matches(
                pexec, sel, spec, emits, ord_, sel_state, pstate, now,
                key_idx=key_idx)
            return pstate, sel_state, out, wake

        return jax.jit(step, donate_argnums=(0, 1))

    steps = {sid: make_step(sid) for sid in spec.stream_ids}

    timer_step = None
    if spec.has_absent:
        any_sid = spec.stream_ids[0]
        schema0 = schemas[any_sid]

        def tstep(pstate, sel_state, now):
            K = pstate.active.shape[0]
            zero_cols = tuple(
                jnp.full((K,), ev.default_value(t), dtype=d)
                for t, d in zip(schema0.types, schema0.dtypes))
            ts_e = jnp.full((K,), now, jnp.int64)
            valid_e = jnp.zeros((K,), jnp.bool_)
            now_k = jnp.full((K,), now, jnp.int64)
            st, emit = pexec.tick(pstate, any_sid, zero_cols, ts_e, valid_e,
                                  now_k)
            emits = jax.tree.map(lambda x: x[None], emit)  # E=1
            ord_ = jnp.zeros((K, 1), jnp.int64)
            sel_state, out, wake = _emit_matches(
                pexec, sel, spec, emits, ord_, sel_state, st, now)
            return st, sel_state, out, wake

        timer_step = jax.jit(tstep, donate_argnums=(0, 1))

    def init_state(K: int):
        return pexec.init_state(K), sel.init_state()

    return PlannedPatternQuery(
        name=name, spec=spec, exec=pexec,
        in_schemas={sid: schemas[sid] for sid in spec.stream_ids},
        out_schema=out_schema,
        output_target=out_target,
        output_event_type=(query.output_stream.output_event_type
                           if query.output_stream and
                           query.output_stream.output_event_type
                           else "CURRENT_EVENTS"),
        steps=steps, timer_step=timer_step, init_state=init_state,
        key_capacity=key_capacity, slots=slots,
        partition_positions=partition_positions)


def _first_schema(spec: PatternSpec, schemas) -> ev.Schema:
    return schemas[spec.stream_ids[0]]


def _emit_matches(pexec: PatternExec, sel: SelectorExec, spec: PatternSpec,
                  emits, ord_, sel_state, pstate, now, key_idx=None):
    """Flatten scan emissions [E,K,P+1] into selector Rows + env."""
    mask = emits["mask"]                       # [E,K,P+1]
    E, K, P1 = mask.shape
    B = E * K * P1

    flat = lambda x: x.reshape(B)
    rows_ts = flat(emits["ts"])
    # order: by arrival (ord), then slot index
    slot_rank = jnp.broadcast_to(
        jnp.arange(P1, dtype=jnp.int64)[None, None, :], mask.shape)
    ord_ekp = jnp.broadcast_to(
        jnp.transpose(ord_)[:, :, None].astype(jnp.int64), mask.shape)
    seq = flat(ord_ekp * (P1 + 1) + slot_rank)

    env: Dict[str, Any] = {"__ts__": rows_ts, "__now__": now}
    for a in spec.all_atoms():
        if a.absent:
            continue
        cap_ts, cap_cols = emits[a.ckey]       # [E,K,P+1,D]
        D = cap_ts.shape[-1]
        env[a.ref] = tuple(c[..., 0].reshape(B) for c in cap_cols)
        for i in range(D):
            env[f"{a.ref}@{i}"] = tuple(
                c[..., i].reshape(B) for c in cap_cols)
        last_i = jnp.clip(flat(emits["count"]).astype(jnp.int32) - 1, 0,
                          D - 1)
        env[f"{a.ref}@-1"] = tuple(
            jnp.take_along_axis(
                c.reshape(B, D), last_i[:, None], axis=1)[:, 0]
            for c in cap_cols)

    if key_idx is not None:
        gslot = flat(jnp.broadcast_to(
            key_idx[None, :, None].astype(jnp.int32), mask.shape))
        gslot = jnp.maximum(gslot, 0)
    else:
        gslot = jnp.zeros((B,), jnp.int32)
    rows = Rows(
        ts=rows_ts,
        kind=jnp.full((B,), ev.CURRENT, jnp.int32),
        valid=flat(mask),
        seq=seq,
        gslot=gslot,
        cols=(),
    )
    sel_state, out = sel.process(sel_state, rows, env)

    # next wakeup: earliest absent deadline
    wake = jnp.asarray(NO_WAKEUP, jnp.int64)
    for a in spec.atoms:
        if a.absent:
            at_pos = jnp.logical_and(pstate.active, pstate.pos == a.pos)
            w = jnp.min(jnp.where(at_pos, pstate.entry_ts + a.waiting_time,
                                  NO_WAKEUP))
            wake = jnp.minimum(wake, w)
    return sel_state, out, wake
