"""Planner + host runtime glue for pattern/sequence queries.

Reference role: CORE/util/parser/StateInputStreamParser.java (NFA build) +
pattern receivers (CORE/query/input/stream/state/receiver/*).  Each pattern
query compiles to one jitted step per input stream; the host groups incoming
events by partition key into a [K, E] layout and the device scan does the
sequential-per-key NFA advance.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..query_api.definition import StreamDefinition
from ..query_api.query import Query, StateInputStream
from . import event as ev
from . import plan_facts
from .executor import CompileError
from .pattern import PatternExec, PatternSpec, linearize, oh_take
from .pattern_block import block_eligible, make_block_step
from .selector import SelectorExec
from .window import NO_WAKEUP, Rows
from .steputil import jit_step, pcast, shard_map

# test hook: force the sequential scan path even for block-eligible specs
# (golden cross-checks compare the two implementations on the same input)
_FORCE_SCAN = False


class StatePacker:
    """Pack a per-key state pytree (array leaves with leading K axis) into
    two blobs: one i32 (i32/f32-bitcast/bool) and one i64, stored [W, K]
    (key axis MINOR).

    Why two blobs: XLA:TPU scatter has a large per-op cost (~7ms for 32k rows
    measured through the axon tunnel), roughly independent of row width.  The
    NFA state has ~24 leaf arrays; scattering each per batch dominated the
    step.  Packing reduces the per-batch key-state update to 2 gathers + 2
    scatters.

    Why [W, K] and not [K, W]: with keys leading, XLA:TPU layout assignment
    picked a key-major {0,1} layout for the [K, W] blobs, so every per-key
    row gather touched W whole (8,128) tiles — ~15 GB of HBM traffic per
    131k-key step (measured).  With keys minor, per-key access rides the
    tiled minor axis and batch key indices arrive sorted (keyslots group
    ascending), so gather/scatter granules are dense.
    """

    def __init__(self, example):
        leaves, self.treedef = jax.tree_util.tree_flatten(example)
        self.recs = []   # (kind, dtype, tail_shape, offset, width)
        self.w32 = 0
        self.w64 = 0
        self.scalars = []
        for i, leaf in enumerate(leaves):
            if leaf.ndim == 0:
                self.recs.append(("scalar", leaf.dtype, (), len(self.scalars),
                                  0))
                self.scalars.append(i)
                continue
            head = leaf.shape[:-1]     # K is the LAST axis on every leaf
            width = 1
            for d in head:
                width *= d
            if leaf.dtype == jnp.int64:
                self.recs.append(("i64", leaf.dtype, head, self.w64, width))
                self.w64 += width
            else:
                self.recs.append(("i32", leaf.dtype, head, self.w32, width))
                self.w32 += width

    def pack(self, state):
        leaves = jax.tree_util.tree_flatten(state)[0]
        K = None
        parts32, parts64, scal = [], [], []
        for leaf, (kind, dtype, head, off, width) in zip(leaves, self.recs):
            if kind == "scalar":
                scal.append(leaf)
                continue
            K = leaf.shape[-1]
            flat = leaf.reshape(width, K)            # pure reshape, K minor
            if kind == "i64":
                parts64.append(flat.astype(jnp.int64))
            else:
                if dtype == jnp.float32:
                    flat = lax.bitcast_convert_type(flat, jnp.int32)
                else:
                    flat = flat.astype(jnp.int32)
                parts32.append(flat)
        b32 = jnp.concatenate(parts32, axis=0) if parts32 else \
            jnp.zeros((0, K), jnp.int32)
        b64 = jnp.concatenate(parts64, axis=0) if parts64 else \
            jnp.zeros((0, K), jnp.int64)
        return b32, b64, tuple(scal)

    def unpack(self, b32, b64, scalars):
        leaves = []
        K = b32.shape[1]
        for kind, dtype, head, off, width in self.recs:
            if kind == "scalar":
                leaves.append(scalars[off])
                continue
            if kind == "i64":
                flat = lax.dynamic_slice_in_dim(b64, off, width, axis=0)
                leaf = flat.reshape(head + (K,))
            else:
                flat = lax.dynamic_slice_in_dim(b32, off, width, axis=0)
                if dtype == jnp.float32:
                    flat = lax.bitcast_convert_type(flat, jnp.float32)
                leaf = flat.reshape(head + (K,))
                if dtype == jnp.bool_:
                    leaf = leaf != 0
                elif dtype != jnp.float32:
                    leaf = leaf.astype(dtype)
            leaves.append(leaf)
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


@dataclasses.dataclass
class PlannedPatternQuery:
    name: str
    spec: PatternSpec
    exec: PatternExec
    in_schemas: Dict[str, ev.Schema]
    out_schema: ev.Schema
    output_target: str
    output_event_type: str
    steps: Dict[str, Callable]          # stream_id -> jitted step
    timer_step: Optional[Callable]
    init_state: Callable                # (K) -> (pattern_state, sel_state)
    key_capacity: int
    slots: int
    partition_positions: Optional[Dict[str, List[int]]] = None
    raw_steps: Optional[Dict[str, Callable]] = None   # unjitted bodies
    mesh: Any = None
    # contiguous-slot fast path: takes a scalar key_lo instead of key_idx and
    # reads/writes the state slab with dynamic slices — generic row
    # gather/scatter on TPU is row-serialized (~0.3us/row; 131k-key batch =
    # ~90ms), a contiguous slice is DMA-speed
    dense_steps: Optional[Dict[str, Callable]] = None
    # ts-delta wire variants (base i64 scalar + delta i32 [B] instead of a
    # fresh i64 [B] ts column); None when unavailable (sharded path)
    steps_w: Optional[Dict[str, Callable]] = None
    dense_steps_w: Optional[Dict[str, Callable]] = None
    # False when the per-key emission cap is an implicit default: overflow
    # then raises instead of dropping rows (@emit(rows=N) opts into capping)
    emit_explicit: bool = True
    # range partitions: stream_id -> host fn(staged) -> (key_cols, valid)
    # overriding positional key extraction (reference:
    # RangePartitionExecutor.java:45)
    partition_key_fns: Optional[Dict[str, Callable]] = None
    # the SelectorExec whose per-key accumulator slabs ride sel_state —
    # purge resets them through bank.specs (init values / slot spaces)
    selector_exec: Any = None
    # UUID() appears in this query: emission materializes sentinels once
    emits_uuid: bool = False
    # per-key emission row cap the steps compiled with (adaptive growth
    # doubles it after an implicit-cap overflow)
    compact_rows: int = 8
    # the un-jitted bodies `steps` was built from (block bodies when the
    # block path is active, else the scan bodies) — @fuse(batches=K) wraps
    # THESE in its lax.scan so fused and sequential execution run the
    # identical per-batch program (core/fusion.py); None on the mesh path
    step_bodies: Optional[Dict[str, Callable]] = None
    # mesh path's @fuse entry: one shard_map dispatch scanning K stacked
    # batches per device (fusion._dispatch_pattern_sharded); None off-mesh
    shard_fused_steps: Optional[Dict[str, Callable]] = None

    # the compact_rows default means "effectively uncapped" for
    # non-partitioned patterns (a per-key cap with K=1 would cap the
    # batch); the sentinel value and its rendering are shared with lint /
    # explain / healthz through core/plan_facts.py
    _UNCAPPED = plan_facts.UNCAPPED_SENTINEL

    def describe(self) -> Dict:
        """Compiled-plan facts for EXPLAIN (observability/explain.py):
        the NFA layout the planner built — key/slot capacities, emission
        cap, which step specializations exist — beyond the query AST."""
        d: Dict[str, Any] = {
            "streams": list(self.spec.stream_ids),
            "nfa_states": self.spec.n_states,
            "state_type": self.spec.state_type,
            "within_ms": self.spec.within,
            "key_capacity": self.key_capacity,
            "nfa_slots_per_key": self.slots,
            "partitioned": bool(self.partition_positions),
            "out_columns": list(self.out_schema.names),
            # per-batch step specializations the runtime can dispatch to
            "ts_delta_wire": self.steps_w is not None,
            "dense_slot_fast_path": self.dense_steps is not None,
            "timer_step": self.timer_step is not None,
        }
        d["emission_cap_rows"] = plan_facts.render_cap(self.compact_rows)
        d["emission_cap_explicit"] = bool(self.emit_explicit)
        if self.mesh is not None:
            d["sharded_over_devices"] = int(self.mesh.devices.size)
            d["shard_fused_step"] = self.shard_fused_steps is not None
        # @serve (serving/): patterns are ring-eligible — wake-bearing
        # batches (within-window timers) still deliver inline, everything
        # else appends to the device ring
        d["serve_eligible"] = True
        return d


def plan_pattern_query(
    query: Query,
    name: str,
    schemas: Dict[str, ev.Schema],
    interner: ev.StringInterner,
    key_capacity: int = 1,
    slots: int = 8,
    count_cap: int = 8,
    partition_positions: Optional[Dict[str, List[int]]] = None,
    partition_key_fns: Optional[Dict[str, Callable]] = None,
    mesh=None,
    script_functions=None,
    compact_rows_override: Optional[int] = None,
) -> PlannedPatternQuery:
    sis = query.input_stream
    assert isinstance(sis, StateInputStream)
    # per-key emission row cap (device output compaction); overflow counted
    # in the out[1] scalar.  Tune with @emit(rows='N') on the query.  Only
    # partitioned queries compact by default: for K=1 a per-key cap would
    # cap the whole batch.  compact_rows_override carries the runtime's
    # adaptive growth after an implicit-cap overflow (state shapes do not
    # depend on the cap, so only the step functions rebuild).
    compact_rows = compact_rows_override or (
        8 if partition_positions else plan_facts.UNCAPPED_SENTINEL)
    emit_explicit = False
    for ann in query.annotations:
        if ann.name.lower() == "emit":
            compact_rows = int(ann.element("rows", compact_rows))
            emit_explicit = True
    spec = linearize(sis, count_cap=count_cap)
    for sid in spec.stream_ids:
        if sid not in schemas:
            raise CompileError(f"undefined stream {sid!r} in pattern")
    pexec = PatternExec(spec, schemas, interner, slots=slots,
                        emit_refs=_used_refs(query, spec),
                        script_functions=script_functions)

    out_target = query.output_stream.target_id if query.output_stream else ""
    # per-key aggregation: the selector's group slots are the partition keys
    group_slots = key_capacity if partition_positions else 64
    sel = SelectorExec(query.selector, pexec.scope,
                       _first_schema(spec, schemas), group_slots,
                       out_target or name, interner)
    if sel.bank.pair_sources:
        raise CompileError(
            "distinctCount/unionSet in pattern queries lands in a later "
            "phase")

    out_def = StreamDefinition(out_target or f"#{name}.out")
    for n, t in zip(sel.out_names, sel.out_types):
        out_def.attribute(n, t)
    out_schema = ev.Schema(out_def, interner)

    P = pexec.P
    refs = [a.ref for a in spec.all_atoms() if not a.absent]
    depths = {a.ref: a.capture_depth for a in spec.all_atoms() if not a.absent}

    packer = StatePacker(pexec.init_state(1))

    def make_step(stream_id: str, dense: bool = False):
        schema = schemas[stream_id]

        def step(packed, sel_state, raw_cols, raw_ts, sel_idx, key_ref, now,
                 in_tabs=()):
            # raw_cols/raw_ts are the UNGROUPED batch [B]; sel_idx [Kb,E]
            # holds batch indices (-1 = padding).  The [Kb,E] gather happens
            # here on device (~60us) so the host ships ~40% fewer bytes and
            # never copies event payloads.
            b32, b64, scalars = packed
            B = raw_ts.shape[0]
            csel = jnp.clip(sel_idx, 0, B - 1)
            cols = tuple(c[csel].astype(d)
                         for c, d in zip(raw_cols, schema.dtypes))
            ts = raw_ts[csel]
            valid = sel_idx >= 0
            ord_ = csel.astype(jnp.int64)
            Kb = ts.shape[0]
            if dense:
                # key_ref is a scalar key_lo: the batch's slots are the
                # contiguous range [key_lo, key_lo+Kb) -> DMA-speed slices
                key_lo = jnp.asarray(key_ref, jnp.int32)
                z = jnp.asarray(0, jnp.int32)
                key_idx = key_lo + jnp.arange(Kb, dtype=jnp.int32)
                sub32 = lax.dynamic_slice(b32, (z, key_lo),
                                          (packer.w32, Kb))
                sub64 = lax.dynamic_slice(b64, (z, key_lo),
                                          (packer.w64, Kb))
            else:
                # generic path: 2 gathers riding the minor (key) axis
                key_idx = key_ref
                sub32, sub64 = b32[:, key_idx], b64[:, key_idx]
            sub = packer.unpack(sub32, sub64, scalars)

            def body(carry, xs):
                st = carry
                cols_e, ts_e, valid_e = xs
                now_k = jnp.where(valid_e, ts_e, now)
                st, emit = pexec.tick(st, stream_id, cols_e, ts_e, valid_e,
                                      now_k, in_tabs)
                return st, emit

            xs = (tuple(c.T for c in cols), ts.T, valid.T)   # scan over E
            sub, emits = lax.scan(body, sub, xs)

            nb32, nb64, nscal = packer.pack(sub)
            if dense:
                z = jnp.asarray(0, jnp.int32)
                key_lo = jnp.asarray(key_ref, jnp.int32)
                b32 = lax.dynamic_update_slice(b32, nb32, (z, key_lo))
                b64 = lax.dynamic_update_slice(b64, nb64, (z, key_lo))
            else:
                # out-of-bounds (padding) rows are dropped by scatter
                b32 = b32.at[:, key_idx].set(nb32, mode="drop")
                b64 = b64.at[:, key_idx].set(nb64, mode="drop")

            sel_state, out, wake = _emit_matches(
                pexec, sel, spec, emits, ord_, sel_state, sub, now,
                key_idx=key_idx, compact_rows=compact_rows)
            return (b32, b64, nscal), sel_state, out, wake

        return step

    raw_steps = {sid: make_step(sid) for sid in spec.stream_ids}

    def wire_ts(body):
        """ts-delta wire variant: the host ships (base i64 scalar,
        delta i32 [B]) instead of a fresh 8-byte-per-event timestamp
        column — fresh H2D bytes halve on a tunneled device where
        transfer of NEW buffers is the measured flagship bottleneck
        (PERF.md lever 1).  The i64 column reconstructs on device inside
        the same jit."""
        def wrapped(packed, sel_state, raw_cols, ts_base, ts_delta,
                    sel_idx, key_ref, now, in_tabs=()):
            raw_ts = jnp.asarray(ts_base, jnp.int64) + \
                ts_delta.astype(jnp.int64)
            return body(packed, sel_state, raw_cols, raw_ts, sel_idx,
                        key_ref, now, in_tabs)
        return wrapped

    dense_steps = None
    steps_w = None
    dense_steps_w = None
    step_bodies = None
    shard_fused_steps = None
    if mesh is None and partition_positions is None and \
            block_eligible(spec) and not _FORCE_SCAN:
        # single-key simple chain: the sequential E-tick scan degrades to
        # interpreter speed (round-4: 776 ev/s); the block path advances a
        # whole chunk in S-1 vectorized stages — see pattern_block.py
        block_bodies = {sid: make_block_step(
            spec, pexec, sel, schemas, packer, sid, compact_rows)
            for sid in spec.stream_ids}
        steps = {sid: jit_step(b, owner=name, donate_argnums=(0, 1))
                 for sid, b in block_bodies.items()}
        steps_w = {sid: jit_step(wire_ts(b), owner=name,
                                 donate_argnums=(0, 1))
                   for sid, b in block_bodies.items()}
        step_bodies = block_bodies
    elif mesh is None:
        steps = {sid: jit_step(body, owner=name, donate_argnums=(0, 1))
                 for sid, body in raw_steps.items()}
        steps_w = {sid: jit_step(wire_ts(body), owner=name,
                                 donate_argnums=(0, 1))
                   for sid, body in raw_steps.items()}
        dense_steps = {sid: jit_step(make_step(sid, dense=True), owner=name,
                                     donate_argnums=(0, 1))
                       for sid in spec.stream_ids}
        dense_steps_w = {sid: jit_step(wire_ts(make_step(sid, dense=True)),
                                       owner=name, donate_argnums=(0, 1))
                         for sid in spec.stream_ids}
        step_bodies = raw_steps
    else:
        steps = {sid: _shard_step(body, mesh, packer, pexec, sel,
                                  owner=name)
                 for sid, body in raw_steps.items()}
        # @fuse over the mesh: scan-of-K-batches inside the shard_map
        # (fusion._dispatch_pattern routes stacks here)
        shard_fused_steps = {
            sid: _shard_fused_step(body, mesh, packer, pexec, sel,
                                   owner=f"fused:{name}")
            for sid, body in raw_steps.items()}

    timer_step = None
    if spec.has_absent:
        any_sid = spec.stream_ids[0]
        schema0 = schemas[any_sid]

        def tstep(packed, sel_state, now, in_tabs=()):
            b32, b64, scalars = packed
            pstate = packer.unpack(b32, b64, scalars)
            K = pstate.active.shape[-1]
            zero_cols = tuple(
                jnp.full((K,), ev.default_value(t), dtype=d)
                for t, d in zip(schema0.types, schema0.dtypes))
            ts_e = jnp.full((K,), now, jnp.int64)
            valid_e = jnp.zeros((K,), jnp.bool_)
            now_k = jnp.full((K,), now, jnp.int64)
            st, emit = pexec.tick(pstate, any_sid, zero_cols, ts_e, valid_e,
                                  now_k, in_tabs)
            emits = jax.tree.map(lambda x: x[None], emit)  # E=1
            ord_ = jnp.zeros((K, 1), jnp.int64)
            sel_state, out, wake = _emit_matches(
                pexec, sel, spec, emits, ord_, sel_state, st, now)
            nb32, nb64, nscalars = packer.pack(st)
            # per-key changed mask so the host marks ONLY mutated keys dirty
            # (a full-slab dirty would turn every incremental snapshot after
            # a timer fire into a full one)
            changed = jnp.any(nb32 != b32, axis=0) | \
                jnp.any(nb64 != b64, axis=0)
            return (nb32, nb64, nscalars), sel_state, out, wake, changed

        timer_step = jit_step(tstep, owner=name,
                              donate_argnums=(0, 1))

    def init_state(K: int):
        return packer.pack(pexec.init_state(K)), sel.init_state()

    return PlannedPatternQuery(
        name=name, spec=spec, exec=pexec,
        in_schemas={sid: schemas[sid] for sid in spec.stream_ids},
        out_schema=out_schema,
        output_target=out_target,
        output_event_type=(query.output_stream.output_event_type
                           if query.output_stream and
                           query.output_stream.output_event_type
                           else "CURRENT_EVENTS"),
        steps=steps, dense_steps=dense_steps,
        steps_w=steps_w, dense_steps_w=dense_steps_w,
        timer_step=timer_step, init_state=init_state,
        key_capacity=key_capacity, slots=slots,
        partition_positions=partition_positions,
        partition_key_fns=partition_key_fns,
        raw_steps=raw_steps, mesh=mesh, emit_explicit=emit_explicit,
        selector_exec=sel, emits_uuid=pexec.scope.uses_uuid,
        compact_rows=compact_rows, step_bodies=step_bodies,
        shard_fused_steps=shard_fused_steps)


def _first_schema(spec: PatternSpec, schemas) -> ev.Schema:
    return schemas[spec.stream_ids[0]]


def _used_refs(query: Query, spec: PatternSpec) -> set:
    """Refs whose captures the selector can touch (emission pruning)."""
    from ..query_api.expression import Variable, walk
    refs = {a.ref for a in spec.all_atoms() if not a.absent}
    sel = query.selector
    if sel.is_select_all:
        return refs      # select * touches everything
    used = set()
    exprs = [oa.expression for oa in sel.selection_list]
    if sel.having_expression is not None:
        exprs.append(sel.having_expression)
    exprs.extend(sel.group_by_list)
    exprs.extend(ob.variable for ob in sel.order_by_list)
    unqualified = False
    for e in exprs:
        for node in walk(e):
            if isinstance(node, Variable):
                if node.stream_id is not None and node.stream_id in refs:
                    used.add(node.stream_id)
                elif node.stream_id is None:
                    unqualified = True
    if unqualified:
        return refs      # can't prove which source an unqualified attr hits
    return used


def _shard_specs(packer: "StatePacker", pexec: PatternExec,
                 sel: SelectorExec):
    """(pattern-state spec, selector-state spec) for the sharded pattern
    layouts — blobs are [W, K] with the key (shard) axis at axis 1;
    selector slabs shard axis 0; scalars replicate."""
    from jax.sharding import PartitionSpec as P

    ex_packed = packer.pack(pexec.init_state(2))
    ex_s = sel.init_state()

    def leaf_spec(x):
        return P() if getattr(x, "ndim", 0) == 0 else P("shard")

    pspec = (P(None, "shard"), P(None, "shard"),
             tuple(P() for _ in ex_packed[2]))
    sspec = jax.tree.map(leaf_spec, ex_s)
    return pspec, sspec


def _shard_local(body):
    """Per-device body shared by the sequential sharded step and the
    fused (scan) variant: replicated inputs are marked device-varying,
    the unmodified single-device `body` runs over local key rows, and
    the replicated outputs merge (header psum, scalar-counter delta
    psum, wake pmin)."""

    def local(packed, sel_state, raw_cols, raw_ts, sel_idx, key_idx, now,
              in_tabs=()):
        b32, b64, scalars = packed
        old_scalars = scalars
        # replicated scalar counters become device-varying inside; mark them
        scalars = tuple(pcast(s, ("shard",), to="varying")
                        for s in scalars)
        raw_cols = tuple(pcast(c, ("shard",), to="varying")
                         for c in raw_cols)
        raw_ts = pcast(raw_ts, ("shard",), to="varying")
        in_tabs = jax.tree.map(
            lambda x: pcast(x, ("shard",), to="varying"), in_tabs)
        ps, ss, out, wake = body((b32, b64, scalars), sel_state, raw_cols,
                                 raw_ts, sel_idx, key_idx, now, in_tabs)
        out = (lax.psum(out[0], "shard"), lax.psum(out[1], "shard")) + out[2:]
        nb32, nb64, nscal = ps
        # re-replicate scalar counters: old + psum(local delta)
        nscal = tuple(
            old + lax.psum(new - pcast(old, ("shard",), to="varying"),
                           "shard")
            for old, new in zip(old_scalars, nscal))
        wake = lax.pmin(wake, "shard")
        return (nb32, nb64, nscal), ss, out, wake

    return local


def _shard_step(body, mesh, packer: "StatePacker", pexec: PatternExec,
                sel: SelectorExec,
                owner=None):
    """Shard the pattern step over the mesh 'shard' axis.

    Design (scaling-book style): partition keys are the shard axis — each
    device owns K/n key rows of NFA + aggregation state, the host routes
    events to their key's shard (sharding/router.py: slot % n), and the
    per-device step is the unmodified single-device body.  Keys are
    independent so the data path needs NO cross-device communication;
    only the scalar next-wakeup reduction (pmin) and the overflow counter
    (psum) ride the ICI.  This replaces the reference's
    thread-per-Disruptor scale-up (CORE/stream/StreamJunction.java:296)
    with SPMD scale-out.
    """
    from jax.sharding import PartitionSpec as P

    pspec, sspec = _shard_specs(packer, pexec, sel)
    bspec = P("shard")    # sharded inputs: [n*Kb, ...] on axis 0
    rspec = P()           # raw event columns [B]: replicated to all shards
    sharded = shard_map(
        _shard_local(body), mesh=mesh,
        in_specs=(pspec, sspec, rspec, rspec, bspec, bspec, P(), P()),
        out_specs=(pspec, sspec, (P(), P(), bspec, bspec, bspec, bspec), P()))
    return jit_step(sharded, owner=owner, donate_argnums=(0, 1))


def _shard_fused_step(body, mesh, packer: "StatePacker", pexec: PatternExec,
                      sel: SelectorExec, owner=None):
    """@fuse(batches=K) over the MESH: one shard_map dispatch whose local
    body is a lax.scan over K stacked batches — per-dispatch overhead
    (and, on a tunneled device, the per-send RTT) divides by K per shard,
    the design lever ROADMAP item 1 names for the sharded serving path.
    The scan sits INSIDE the shard_map, so every iteration runs the same
    per-device program as the sequential sharded step and parity is
    byte-identical; stacked inputs carry a leading [K] axis with the
    sharded [n*Kb] axes shifted to axis 1."""
    from jax.sharding import PartitionSpec as P
    from .steputil import strongify

    pspec, sspec = _shard_specs(packer, pexec, sel)
    local = _shard_local(body)
    bspec2 = P(None, "shard")   # stacked sharded inputs: [K, n*Kb, ...]

    def fused_local(carry, xs, in_tabs):
        def scan_body(c, x):
            packed, sel_state = c
            raw_cols, raw_ts, sel_idx, key_idx, now = x
            ps, ss, out, _wake = local(packed, sel_state, raw_cols,
                                       raw_ts, sel_idx, key_idx, now,
                                       in_tabs)
            return strongify((ps, ss)), out
        return lax.scan(scan_body, carry, xs)

    sharded = shard_map(
        fused_local, mesh=mesh,
        in_specs=((pspec, sspec), (P(), P(), bspec2, bspec2, P()), P()),
        out_specs=((pspec, sspec),
                   (P(), P(), bspec2, bspec2, bspec2, bspec2)))
    return jit_step(sharded, owner=owner, donate_argnums=(0,))


def _emit_matches(pexec: PatternExec, sel: SelectorExec, spec: PatternSpec,
                  emits, ord_, sel_state, pstate, now, key_idx=None,
                  compact_rows: int = 8):
    """Flatten scan emissions [E,P+1,K] into selector Rows + env, then
    compact the selector's OUTPUT rows per key.

    The selector over the full E*(P+1)*K grid is cheap (elementwise, XLA
    fuses it); only the final output rows are compacted, [EP,K] -> [R,K],
    as a one-hot contraction over the tiny EP axis — no device gathers (a
    searchsorted/sort compaction costs ~80ms at 131k keys: TPU lowers both
    to serialized gathers; compacting the ~25 capture arrays instead of the
    ~7 output arrays costs GBs of HBM traffic).  Valid rows beyond R
    matches per key per batch are counted in the out[1] dropped scalar."""
    mask = emits["mask"]                       # [E,P+1,K]
    E, P1, K = mask.shape
    EP = E * P1
    B = EP * K

    flat = lambda x: x.reshape(B)
    rows_ts = flat(emits["ts"])
    # order: by arrival (ord), then slot index
    slot_rank = jnp.broadcast_to(
        jnp.arange(P1, dtype=jnp.int64)[None, :, None], mask.shape)
    ord_ekp = jnp.broadcast_to(
        jnp.transpose(ord_)[:, None, :].astype(jnp.int64), mask.shape)
    seq = flat(ord_ekp * (P1 + 1) + slot_rank)

    env: Dict[str, Any] = {"__ts__": rows_ts, "__now__": now}
    for a in spec.all_atoms():
        if a.absent or a.ckey not in emits:
            continue
        cap_ts, cap_cols = emits[a.ckey]       # [E,P+1,D,K]
        D = cap_ts.shape[2]
        env[a.ref] = tuple(c[:, :, 0, :].reshape(B) for c in cap_cols)
        for i in range(D):
            env[f"{a.ref}@{i}"] = tuple(
                c[:, :, i, :].reshape(B) for c in cap_cols)
        # e1[last] = deepest FILLED capture row; the count scalar is
        # position-local (resets when a fork advances past the count atom)
        # so the fill depth derives from the capture ts plane (unfilled
        # rows hold -1; a real event at timestamp 0 still counts)
        nfill = jnp.sum((cap_ts >= 0).astype(jnp.int32),
                        axis=2)                         # [E,P+1,K]
        last_i = jnp.clip(nfill - 1, 0, D - 1)
        last_oh = (jnp.arange(D)[None, None, :, None] ==
                   last_i[:, :, None, :])               # [E,P+1,D,K]
        env[f"{a.ref}@-1"] = tuple(
            flat(oh_take(c, last_oh, 2)) for c in cap_cols)

    if key_idx is not None:
        gslot = flat(jnp.broadcast_to(
            key_idx[None, None, :].astype(jnp.int32), mask.shape))
        gslot = jnp.maximum(gslot, 0)
    else:
        gslot = jnp.zeros((B,), jnp.int32)
    rows = Rows(
        ts=rows_ts,
        kind=jnp.full((B,), ev.CURRENT, jnp.int32),
        valid=flat(mask),
        seq=seq,
        gslot=gslot,
        cols=(),
    )
    sel_state, out = sel.process(sel_state, rows, env)

    ots, okind, ovalid, ocols = out
    R = min(compact_rows, EP)
    if R < EP:
        v2 = ovalid.reshape(EP, K)
        rank = jnp.cumsum(v2.astype(jnp.int32), axis=0) - 1
        keep_oh = jnp.logical_and(
            jnp.arange(R, dtype=jnp.int32)[:, None, None] == rank[None],
            v2[None])                          # [R,EP,K]
        cmask = jnp.any(keep_oh, axis=1)       # [R,K]
        n_valid = jnp.sum(cmask.astype(jnp.int64))
        n_dropped = jnp.sum(v2.astype(jnp.int64)) - n_valid

        def cmp(x):                            # [B] -> [R*K]
            return oh_take(x.reshape(EP, K)[None], keep_oh, 1).reshape(R * K)

        out = (cmp(ots), cmp(okind), cmask.reshape(R * K),
               tuple(cmp(c) for c in ocols))
    else:
        n_valid = jnp.sum(ovalid.astype(jnp.int64))
        n_dropped = jnp.zeros((), jnp.int64)
    # leading scalars: valid-row count (drainer skips empty outputs with one
    # 16-byte read) and overflow count (rows beyond R matches/key/batch)
    out = (n_valid, n_dropped) + out

    # next wakeup: earliest absent deadline (standalone `not X for t` atoms
    # and timed absent sides of logical pairs whose wait hasn't elapsed)
    wake = jnp.asarray(NO_WAKEUP, jnp.int64)
    for a in spec.atoms:
        if a.absent:
            at_pos = jnp.logical_and(pstate.active, pstate.pos == a.pos)
            w = jnp.min(jnp.where(at_pos, pstate.entry_ts + a.waiting_time,
                                  NO_WAKEUP))
            wake = jnp.minimum(wake, w)
        elif a.partner is not None and a.partner.absent and \
                a.partner.waiting_time is not None:
            at_pos = jnp.logical_and(
                jnp.logical_and(pstate.active, pstate.pos == a.pos),
                (pstate.lmask & 2) == 0)
            w = jnp.min(jnp.where(
                at_pos, pstate.entry_ts + a.partner.waiting_time, NO_WAKEUP))
            wake = jnp.minimum(wake, w)
    return sel_state, out, wake
