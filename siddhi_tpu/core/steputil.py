"""Step-function hygiene shared by every runtime's jitted step.

Reference behavior (what): the reference's per-event processors are plain
Java — object identity is stable, so a processor never "recompiles"
mid-stream (JoinProcessor.java, StreamPreStateProcessor.java run the same
bytecode for every event).

TPU design (how): our steps are jit-compiled `(state, batch) -> (state',
out)` programs, so the analogous guarantee is *compile-signature
stability*: the state a step RETURNS must have exactly the avals of the
state it ACCEPTS, or the very next call re-traces and re-compiles — a
sub-second stall on CPU and a **minutes-long** stall through the remote
TPU tunnel.  The one way a shape-stable pytree drifts is jax weak typing:
an arithmetic mix of a Python scalar and an array yields `weak_type=True`
leaves, while host-staged init state is strong-typed, so the first timed
batch after warmup recompiles every step (observed: the round-4
windowed_join p99 of 2150ms vs p50 14.9ms was exactly two such
recompiles).  `strongify` canonicalizes every returned leaf to its strong
dtype (a no-op in XLA for already-strong leaves); `jit_step` wraps a step
so all outputs are canonicalized before they leave the jit boundary.
"""
from __future__ import annotations

import functools

import jax


def _strong_leaf(x):
    if isinstance(x, (bool, int, float, complex)):
        # a literal scalar leaf would leave the jit boundary weak-typed;
        # canonicalize it to the strong default dtype for its kind
        a = jax.numpy.asarray(x)
        return jax.lax.convert_element_type(a, a.dtype)
    aval = getattr(x, "aval", None)
    weak = aval.weak_type if aval is not None else \
        getattr(x, "weak_type", False)
    if weak:
        return jax.lax.convert_element_type(x, x.dtype)
    return x


def strongify(tree):
    """Canonicalize every weak-typed array leaf to its strong dtype."""
    return jax.tree.map(_strong_leaf, tree)


def jit_step(fn, **jit_kwargs):
    """`jax.jit` with compile-signature-stable outputs: every returned
    leaf is strong-typed, so feeding returned state back into the step
    can never re-trace.  Drop-in for `jax.jit(fn, donate_argnums=...)`."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        return strongify(fn(*args, **kwargs))

    return jax.jit(wrapped, **jit_kwargs)
