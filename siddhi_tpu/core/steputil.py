"""Step-function hygiene shared by every runtime's jitted step.

Reference behavior (what): the reference's per-event processors are plain
Java — object identity is stable, so a processor never "recompiles"
mid-stream (JoinProcessor.java, StreamPreStateProcessor.java run the same
bytecode for every event).

TPU design (how): our steps are jit-compiled `(state, batch) -> (state',
out)` programs, so the analogous guarantee is *compile-signature
stability*: the state a step RETURNS must have exactly the avals of the
state it ACCEPTS, or the very next call re-traces and re-compiles — a
sub-second stall on CPU and a **minutes-long** stall through the remote
TPU tunnel.  The one way a shape-stable pytree drifts is jax weak typing:
an arithmetic mix of a Python scalar and an array yields `weak_type=True`
leaves, while host-staged init state is strong-typed, so the first timed
batch after warmup recompiles every step (observed: the round-4
windowed_join p99 of 2150ms vs p50 14.9ms was exactly two such
recompiles).  `strongify` canonicalizes every returned leaf to its strong
dtype (a no-op in XLA for already-strong leaves); `jit_step` wraps a step
so all outputs are canonicalized before they leave the jit boundary.
"""
from __future__ import annotations

import functools

import jax

# jax moved shard_map from jax.experimental to the top level; support both
# so the mesh paths run on every jaxlib this repo meets (the container
# bakes 0.4.x, newer deployments ship it at jax.shard_map)
try:
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map  # noqa: F401

# lax.pcast (replicated<->varying annotation cast inside shard_map) is a
# newer-jax API; it is data-identity, and 0.4.x's shard_map rep-inference
# handles replicated/varying mixing on its own, so identity is the correct
# fallback
try:
    pcast = jax.lax.pcast
except AttributeError:  # pragma: no cover - version-dependent
    def pcast(x, axes, to="varying"):
        return x


def _strong_leaf(x):
    if isinstance(x, (bool, int, float, complex)):
        # a literal scalar leaf would leave the jit boundary weak-typed;
        # canonicalize it to the strong default dtype for its kind
        a = jax.numpy.asarray(x)
        return jax.lax.convert_element_type(a, a.dtype)
    aval = getattr(x, "aval", None)
    weak = aval.weak_type if aval is not None else \
        getattr(x, "weak_type", False)
    if weak:
        return jax.lax.convert_element_type(x, x.dtype)
    return x


def strongify(tree):
    """Canonicalize every weak-typed array leaf to its strong dtype."""
    return jax.tree.map(_strong_leaf, tree)


def fuse_step(body, owner=None):
    """K query steps in ONE device dispatch: `body(carry, x, const) ->
    (carry', y)` becomes a jitted `fused(carry, xs, const) -> (carry',
    ys)` running `lax.scan` over the leading [K] axis of every `xs` leaf.

    This is the deep-batching lever PERF.md names: per-dispatch and
    per-fetch fixed costs (a ~73-95 ms tunnel round-trip per send on the
    remote TPU; Python dispatch overhead on CPU) divide by K because K
    staged micro-batches ride one transfer, one XLA execution, and one
    emission-header fetch.  State threads through the scan carry exactly
    as it threads through K sequential `jit_step` calls; the carry is
    `strongify`-ed every iteration so a weak-typed leaf can never make
    the carry aval drift mid-scan (the same guarantee jit_step gives at
    the jit boundary).

    `owner` should be the fused recompile owner (`fused:<query>`) so a
    K-change or shape-change recompile is attributed in /metrics instead
    of appearing as a silent re-trace of the base step."""

    def fused(carry, xs, const):
        def scan_body(c, x):
            c2, y = body(c, x, const)
            return strongify(c2), y
        return jax.lax.scan(scan_body, carry, xs)

    return jit_step(fused, owner=owner, donate_argnums=(0,))


def jit_step(fn, owner=None, **jit_kwargs):
    """`jax.jit` with compile-signature-stable outputs: every returned
    leaf is strong-typed, so feeding returned state back into the step
    can never re-trace.  Drop-in for `jax.jit(fn, donate_argnums=...)`.

    `owner` labels this step for recompile accounting: the wrapped body
    only executes while jax is TRACING a new signature, so recording there
    counts exactly the compile events — with the triggering abstract
    shapes — at zero steady-state cost (observability/recompile.py).  A
    DETAIL-level pipeline trace active on the tracing thread additionally
    gets a `compile` span, making a recompile-stalled batch self-evident
    in its trace dump."""
    from ..observability.recompile import RECOMPILES
    from ..observability import tracing
    label = owner or getattr(fn, "__qualname__", None) or "step"
    # last-traced argument avals, captured for EXPLAIN: observability/
    # explain.py re-lowers the jitted step from these ShapeDtypeStructs to
    # run XLA cost analysis on exactly the signature that actually ran
    # (specs are tiny host objects — no arrays are retained)
    spec_holder = {"argspecs": None}

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if RECOMPILES.suppressed():
            # diagnostic re-trace (EXPLAIN cost analysis): no recompile
            # accounting AND no compile-gate admission — diagnostics
            # must never queue behind (or penalize) real compiles
            return strongify(fn(*args, **kwargs))
        # this body only executes while jax traces a NEW signature, so
        # the shared compile-admission gate (core/admission.py) wraps
        # exactly the compile events: traces serialize process-wide and
        # an app over its admission.max.recompiles.per.min budget pays
        # its penalty before contending — a storming tenant's compiles
        # queue behind everyone else instead of in front
        from .admission import COMPILE_GATE
        with COMPILE_GATE.admit(label):
            RECOMPILES.record(label, args)
            try:
                spec_holder["argspecs"] = jax.tree.map(
                    lambda x: jax.ShapeDtypeStruct(x.aval.shape,
                                                   x.aval.dtype), args)
            except Exception:  # noqa: BLE001 — accounting must not break
                pass           # a trace (e.g. non-array leaves)
            tr = tracing.active()
            if tr is None:
                return strongify(fn(*args, **kwargs))
            with tracing.span("compile", owner=label):
                return strongify(fn(*args, **kwargs))

    jitted = jax.jit(wrapped, **jit_kwargs)
    try:
        jitted._siddhi_owner = label
        jitted._siddhi_argspec = spec_holder
    except Exception:  # noqa: BLE001 — attribute support is best-effort
        pass
    return jitted
