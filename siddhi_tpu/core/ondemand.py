"""On-demand (store) queries: one-shot reads/writes on tables, named windows
and aggregations.

Reference behavior (what): CORE/util/parser/OnDemandQueryParser.java:101 and
CORE/query/{Find,Select,Insert,Update,Delete,UpdateOrInsert}OnDemandQueryRuntime
— `runtime.query("from T on cond select ...")` executes immediately against
the store's current contents and returns Event[].

TPU-native design (how): the store's contents are already columnar device/
host arrays (table rows, window buffer, aggregation bucket snapshot); an
on-demand query is one vectorized filter + reduce over them — no object
iteration.  Aggregates here are terminal (one result per group), not
incremental, so they reduce with plain segmented numpy ops.
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..query_api.expression import AttributeFunction, Variable
from . import event as ev
from .executor import CompileError, Scope, compile_expression


def _store_rows(rt, store_id: str, within, per):
    """-> (schema, cols [np arrays], valid mask, scope_key)."""
    if store_id in rt.tables:
        t = rt.tables[store_id]
        return (t.schema, [np.asarray(c) for c in t.cols],
                np.asarray(t.valid))
    if store_id in rt.named_windows:
        nw = rt.named_windows[store_id]
        buf = nw.wproc.current_buffer(nw.state)
        if buf is None:
            raise CompileError(
                f"window type {nw.wproc.name!r} does not expose contents "
                f"for on-demand queries")
        return (nw.schema, [np.asarray(c) for c in buf.cols],
                np.asarray(buf.alive))
    if store_id in rt.aggregations:
        from .aggregation import parse_per, parse_within
        agg = rt.aggregations[store_id]
        rng = parse_within(within) if within is not None else None
        if per is None:
            raise CompileError("aggregation on-demand query needs `per`")
        ts, cols = agg.snapshot_rows(parse_per(per), rng)
        return (agg.make_schema(), [np.asarray(c) for c in cols],
                np.ones((ts.shape[0],), np.bool_))
    raise CompileError(f"no table/window/aggregation named {store_id!r}")


_AGG_FNS = ("sum", "count", "avg", "min", "max", "distinctCount")


class OnDemandPlanMemo:
    """Per-query compile cache so a repeated on-demand query does zero
    re-planning (reference: SiddhiAppRuntimeImpl.java:304-367 keeps up to
    50 compiled OnDemandQueryRuntimes keyed by query string).

    Keys are id(expr) of AST nodes: valid because the memo lives in the
    same LRU entry as the parsed AST, so the nodes stay alive and their
    ids stable for the memo's whole lifetime.  `plans` counts actual
    compile/plan events (tests assert it stops growing on a cache hit)."""

    def __init__(self):
        self.exprs = {}
        self.table_plans = {}
        self.selections = {}
        self.plans = 0

    def split_selection(self, selector, schema):
        # cached so `select *`'s synthesized Variables keep stable ids
        k = id(selector)
        if k not in self.selections:
            self.selections[k] = _split_selection(selector, schema)
        return self.selections[k]

    def compile(self, expr, scope):
        c = self.exprs.get(id(expr))
        if c is None:
            c = compile_expression(expr, scope)
            self.exprs[id(expr)] = c
            self.plans += 1
        return c

    def plan_condition(self, table, cond_expr, scope, key):
        k = id(cond_expr)
        if k not in self.table_plans:
            self.table_plans[k] = table.plan_condition(
                cond_expr, scope, table_id=key, unqualified_is_table=True)
            self.plans += 1
        return self.table_plans[k]


class _NoMemo:
    """Uncached fallback for direct OnDemandQuery-object invocations."""

    plans = 0

    def split_selection(self, selector, schema):
        return _split_selection(selector, schema)

    def compile(self, expr, scope):
        return compile_expression(expr, scope)

    def plan_condition(self, table, cond_expr, scope, key):
        return table.plan_condition(cond_expr, scope, table_id=key,
                                    unqualified_is_table=True)


def _split_selection(selector, schema) -> Tuple[list, bool]:
    """[(name, expr, agg_fn_or_None)] for each output."""
    out = []
    has_agg = False
    sel_list = selector.selection_list
    if not sel_list:  # select *
        return ([(n, Variable(n), None) for n in schema.names], False)
    for oa in sel_list:
        e = oa.expression
        name = oa.rename or (e.attribute_name if isinstance(e, Variable)
                             else "expr")
        if isinstance(e, AttributeFunction) and not e.namespace and \
                e.name in _AGG_FNS:
            has_agg = True
            out.append((name, e, e.name))
        else:
            out.append((name, e, None))
    return out, has_agg


def execute_on_demand(rt, oq, memo=None) -> List[ev.Event]:
    """Entry point used by SiddhiAppRuntime.query()."""
    if memo is None:
        memo = _NoMemo()
    if oq.type == "INSERT" and oq.input_store is None:
        return _insert_constant(rt, oq)
    store = oq.input_store
    schema, cols, valid, = _store_rows(rt, store.store_id, store.within,
                                       store.per)
    key = store.alias if getattr(store, "alias", None) else store.store_id

    scope = Scope()
    scope.interner = rt.interner
    scope.add_source(key, schema)

    env = {key: tuple(np.asarray(c) for c in cols),
           "__ts__": np.zeros(valid.shape, np.int64),
           "__now__": np.int64(rt.timestamp_millis())}
    mask = valid.copy()
    if store.on_condition is not None:
        c = memo.compile(store.on_condition, scope)
        if c.type != "BOOL":
            raise CompileError("on-condition must be boolean")
        table = rt.tables.get(store.store_id)
        sel = (_indexed_row_mask(table, store.on_condition, key, schema,
                                 scope, env, mask, c, memo)
               if table is not None else None)
        if sel is not None:
            mask &= sel
        else:
            if table is not None:
                table.index_stats["dense"] += 1
            mask &= np.asarray(c.fn(env)).astype(bool)

    if oq.type == "FIND":
        return _find(rt, oq, scope, schema, env, mask, key, memo)

    # write ops route the found rows through the table-op machinery
    sel_events = _find(rt, oq, scope, schema, env, mask, key, memo)
    tgt = oq.output_stream.target_id
    if tgt not in rt.tables:
        if oq.type == "INSERT":
            raise CompileError(f"no table named {tgt!r}")
        raise CompileError(f"on-demand {oq.type} target must be a table")
    _apply_write(rt, oq, sel_events, schema, key)
    return sel_events


def _indexed_row_mask(table, cond_expr, key, schema, scope, env, valid,
                      compiled_full, memo):
    """Index-aware on-demand condition (reference: the store-query path of
    CollectionExpressionParser + IndexOperator.find). Returns a row mask, or
    None when the condition has no usable indexed conjunct.

    The probe only NARROWS: the full compiled condition re-evaluates on the
    candidate rows, keeping exact dense semantics under dtype casts and
    probe-structure staleness (same contract as TableRuntime._match)."""
    tc = memo.plan_condition(table, cond_expr, scope, key)
    plan = tc.plan
    if plan is None:
        return None
    rv = np.asarray(memo.compile(plan.rhs, scope).fn(env))
    val = rv.reshape(-1)[0]
    if plan.kind == "eq":
        cand, ok = table._probe_candidates(
            plan.pos, np.asarray([val]))
        rows = cand[0][ok[0]].astype(np.int64)
    else:
        rows = table.indexes[plan.pos].rows_range(
            np.asarray(table.valid), plan.op, val)
    mask = np.zeros(valid.shape, bool)
    rows = rows[rows < valid.shape[0]]
    mask[rows] = True
    mask &= valid
    if mask.any():
        ridx = np.nonzero(mask)[0]
        env_sub = dict(env)
        env_sub[key] = tuple(np.asarray(cc)[ridx] for cc in env[key])
        env_sub["__ts__"] = np.asarray(env["__ts__"])[ridx]
        rmask = np.asarray(compiled_full.fn(env_sub))
        mask[ridx] &= np.broadcast_to(rmask.astype(bool), ridx.shape)
    table.index_stats["indexed"] += 1
    return mask


def _result_schema(names, types, interner):
    from ..query_api.definition import StreamDefinition
    sdef = StreamDefinition("#ondemand")
    for n, t in zip(names, types):
        sdef.attribute(n, t)
    return ev.Schema(sdef, interner)


def _find(rt, oq, scope, schema, env, mask, key, memo) -> List[ev.Event]:
    sel = oq.selector
    items, has_agg = memo.split_selection(sel, schema)
    n_rows = int(mask.sum())

    # group-by columns
    gb_names = [v.attribute_name for v in (sel.group_by_list or [])]
    gb_pos = [schema.position(n) for n in gb_names]

    idx = np.nonzero(mask)[0]
    gcols = [np.asarray(env[key][p])[idx] for p in gb_pos]
    if gb_pos:
        stacked = np.stack([c.view(np.int64) if c.dtype.kind == "f"
                            else c.astype(np.int64) for c in gcols])
        uniq, inv = np.unique(stacked, axis=1, return_inverse=True)
        n_groups = uniq.shape[1]
    else:
        inv = np.zeros((idx.size,), np.int64)
        n_groups = 1 if (has_agg and idx.size) or not has_agg else 0

    out_cols = []
    out_names = []
    out_types = []
    for name, expr, agg in items:
        out_names.append(name)
        if agg is None:
            c = memo.compile(expr, scope)
            raw = np.asarray(c.fn(env))
            if raw.ndim == 0:
                raw = np.broadcast_to(raw, mask.shape)
            vals = raw[idx] if idx.size else \
                np.zeros((0,), ev.np_dtype(c.type))
            out_types.append(c.type)
            if has_agg or gb_pos:
                # per-group representative (first row of group)
                rep = np.zeros((n_groups,), vals.dtype if idx.size else
                               ev.np_dtype(c.type))
                if idx.size:
                    first = {}
                    for r, g in enumerate(inv):
                        if g not in first:
                            first[g] = r
                    for g, r in first.items():
                        rep[g] = vals[r]
                out_cols.append(rep)
            else:
                out_cols.append(vals)
            continue
        # aggregate (null inputs skipped, empty aggregates return null —
        # same contract as the streaming AggregatorBank)
        if agg == "count":
            vals = np.ones((idx.size,), np.float64)
            nul = np.zeros((idx.size,), bool)
            out_types.append("LONG")
        else:
            c = memo.compile(expr.parameters[0], scope)
            raw_t = np.asarray(c.fn(env))
            if raw_t.ndim == 0:
                raw_t = np.broadcast_to(raw_t, mask.shape)
            rv = raw_t[idx] if idx.size else \
                np.zeros((0,), ev.np_dtype(c.type))
            nul = np.asarray(ev.null_mask(rv, c.type))
            vals = rv.astype(np.float64)
            out_types.append("DOUBLE" if agg in ("avg",) else
                             ("LONG" if c.type in ("INT", "LONG") and
                              agg in ("sum", "min", "max") else c.type
                              if agg in ("min", "max") else "DOUBLE"))
        out_t = out_types[-1]
        nullv = float(ev.null_value(out_t)) if out_t != "LONG" \
            else float(ev.NULL_LONG)
        nonnull = np.zeros((max(n_groups, 1),), np.float64)
        np.add.at(nonnull, inv, (~nul).astype(np.float64))
        acc = np.zeros((max(n_groups, 1),), np.float64)
        if agg in ("sum", "count"):
            np.add.at(acc, inv, np.where(nul, 0.0, vals))
            if agg == "sum":
                acc = np.where(nonnull > 0, acc, nullv)
        elif agg == "avg":
            cnt = np.zeros_like(acc)
            np.add.at(acc, inv, np.where(nul, 0.0, vals))
            np.add.at(cnt, inv, (~nul).astype(np.float64))
            acc = np.where(cnt > 0, acc / np.maximum(cnt, 1), np.nan)
        elif agg == "min":
            acc[:] = np.inf
            np.minimum.at(acc, inv, np.where(nul, np.inf, vals))
            acc = np.where(nonnull > 0, acc, nullv)
        elif agg == "max":
            acc[:] = -np.inf
            np.maximum.at(acc, inv, np.where(nul, -np.inf, vals))
            acc = np.where(nonnull > 0, acc, nullv)
        elif agg == "distinctCount":
            acc = np.zeros((max(n_groups, 1),), np.float64)
            for g in range(n_groups):
                acc[g] = np.unique(vals[inv == g]).size
        out_cols.append(acc[:n_groups])

    res_schema = _result_schema(out_names, out_types, rt.interner)
    n_out = n_groups if (has_agg or gb_pos) else idx.size

    # having / order by / limit
    henv = {"#out": tuple(np.asarray(c) for c in out_cols)}
    keep = np.ones((n_out,), bool)
    if sel.having_expression is not None:
        hscope = Scope()
        hscope.interner = rt.interner
        hscope.add_source("#out", res_schema)
        hc = memo.compile(sel.having_expression, hscope)
        keep &= np.asarray(hc.fn(henv)).astype(bool)[:n_out]
    sel_idx = np.nonzero(keep)[0]
    if sel.order_by_list:
        keys = []
        for ob in reversed(sel.order_by_list):
            p = out_names.index(ob.variable.attribute_name)
            col = np.asarray(out_cols[p])[sel_idx]
            keys.append(-col if ob.order == "DESC" else col)
        order = np.lexsort(keys)
        sel_idx = sel_idx[order]
    if sel.limit is not None:
        off = sel.offset or 0
        sel_idx = sel_idx[off:off + sel.limit]
    elif sel.offset:
        sel_idx = sel_idx[sel.offset:]

    now = rt.timestamp_millis()
    events = []
    for r in sel_idx:
        data = []
        for c, t in zip(out_cols, out_types):
            v = c[r]
            data.append(res_schema.decode_value(t, v))
        events.append(ev.Event(now, data))
    return events


def _insert_constant(rt, oq) -> List[ev.Event]:
    """`select <constants> insert into T` form."""
    tgt = oq.output_stream.target_id
    if tgt not in rt.tables:
        raise CompileError(f"no table named {tgt!r}")
    table = rt.tables[tgt]
    scope = Scope()
    scope.interner = rt.interner
    if not oq.selector.selection_list:
        raise CompileError("constant insert needs an explicit select list")
    env = {"__ts__": np.zeros((1,), np.int64),
           "__now__": np.int64(rt.timestamp_millis())}
    data = []
    for oa in oq.selector.selection_list:
        c = compile_expression(oa.expression, scope)
        v = np.asarray(c.fn(env))
        data.append(table.schema.decode_value(c.type, v.reshape(()).item()
                                              if v.shape == () or v.size == 1
                                              else v.flat[0]))
    e = ev.Event(rt.timestamp_millis(), data)
    staged = ev.pack_np(table.schema, [e])
    batch = staged.to_device(table.schema)
    table.insert(batch, staged)
    return [e]


def _apply_write(rt, oq, sel_events, store_schema, key) -> None:
    """UPDATE / DELETE / UPDATE_OR_INSERT / INSERT with a FROM store."""
    from ..query_api.query import DeleteStream, UpdateOrInsertStream
    out_stream = oq.output_stream
    tgt = out_stream.target_id
    table = rt.tables[tgt]
    # build an output-events scope like the streaming table-op path
    items, _ = _split_selection(oq.selector, store_schema)
    names = [n for n, _, _ in items]
    if not sel_events:
        if oq.type != "INSERT":
            return
    # re-stage selected events columnar
    from ..query_api.definition import StreamDefinition
    sdef = StreamDefinition("#sel")
    if sel_events:
        for n, v in zip(names, sel_events[0].data):
            t = ("STRING" if isinstance(v, str) else
                 "DOUBLE" if isinstance(v, float) else "LONG")
            sdef.attribute(n, t)
    sschema = ev.Schema(sdef, rt.interner)
    staged = ev.pack_np(sschema, sel_events)
    batch = staged.to_device(sschema)

    if oq.type == "INSERT":
        if len(table.schema.names) != len(names):
            raise CompileError("insert arity does not match table")
        tstaged = ev.pack_np(table.schema, sel_events)
        table.insert(tstaged.to_device(table.schema), tstaged)
        return

    cscope = Scope()
    cscope.interner = rt.interner
    cscope.add_source("#sel", sschema)
    cscope.add_source(tgt, table.schema, default=False)
    cond_expr = (out_stream.on_delete_expression
                 if isinstance(out_stream, DeleteStream)
                 else out_stream.on_update_expression)
    cond = table.plan_condition(cond_expr, cscope)
    set_fns = []
    us = getattr(out_stream, "update_set", None)
    if us is not None:
        for sa in us.set_attribute_list:
            pos = table.schema.position(sa.table_variable.attribute_name)
            e = compile_expression(sa.value_expression, cscope)
            set_fns.append((pos, e.fn))
    elif not isinstance(out_stream, DeleteStream):
        from ..query_api.expression import Variable as V
        for n in table.schema.names:
            if n in sschema.names:
                e = compile_expression(V(n, stream_id="#sel"), cscope)
                set_fns.append((table.schema.position(n), e.fn))

    if isinstance(out_stream, DeleteStream):
        table.delete_where(cond, "#sel", batch)
    elif isinstance(out_stream, UpdateOrInsertStream):
        table.update_where(cond, "#sel", batch, set_fns, upsert=True,
                           staged=staged)
    else:
        table.update_where(cond, "#sel", batch, set_fns)
