"""Query planner: query_api AST -> compiled, jitted step functions.

Reference role (what): CORE/util/parser/QueryParser.java:90 +
SingleInputStreamParser/SelectorParser/OutputParser — there the "plan" is a
graph of interpreter objects.  Here each query compiles to ONE pure function
    step(state, batch, gslot, now) -> (state', output rows, next_wakeup)
traced and compiled once per batch bucket by XLA, with all filters, the
window, aggregation scans and projections fused into a single device program.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..query_api.definition import StreamDefinition
from ..query_api.query import (
    Filter,
    Query,
    SingleInputStream,
    StreamFunction,
    Window,
)
from . import event as ev
from .executor import CompileError, Scope, compile_expression
from .steputil import jit_step, pcast, shard_map
from .keyslots import SlotAllocator
from .selector import SelectorExec
from .window import NoWindow, Rows, WindowProcessor, create_window


@dataclasses.dataclass
class PlannedQuery:
    """Compiled single-input query."""

    name: str
    input_stream_id: str
    in_schema: ev.Schema
    out_schema: ev.Schema
    output_target: str                 # target stream/table id ('' => return)
    output_event_type: str             # CURRENT_EVENTS/EXPIRED_EVENTS/ALL_EVENTS
    window: WindowProcessor
    group_by_positions: List[int]
    selector_exec: SelectorExec
    step: Callable                     # jitted
    init_state: Callable
    slot_allocator: Optional[SlotAllocator]
    batch_capacity: int
    needs_timer: bool
    in_deps: List[str] = dataclasses.field(default_factory=list)
    # range partitions: host fn(staged) -> (key_id col int32, valid mask);
    # rows matching no range are excluded (reference:
    # RangePartitionExecutor.java:45 returns null -> event dropped)
    partition_key_fn: Optional[Callable] = None
    # keyed windows (windows inside partitions): one window state per
    # partition key, vmapped over the key axis
    keyed_window: bool = False
    window_key_allocator: Optional[SlotAllocator] = None
    window_key_positions: Optional[List[int]] = None
    key_capacity: int = 0
    # distinctCount: (pair allocator, value-column position) per call —
    # (group, value) pairs resolve to refcount slots on the host
    pair_allocs: List[Tuple[SlotAllocator, int]] = \
        dataclasses.field(default_factory=list)
    # set when the windowless group-by step is sharded over a device mesh
    # (slot s lives at state row (s % n) * (G/n) + s // n — purge resets
    # must remap through this layout, _PartitionPurger)
    mesh: Any = None
    # set when the keyed-window slab is sharded (key k at row
    # (k % n) * (K/n) + k // n; selector state stays replicated)
    keyed_mesh: Any = None
    # UUID() appears in this query: emission materializes sentinels once
    emits_uuid: bool = False
    # un-jitted step body for @fuse(batches=K) scan fusion (core/fusion.py);
    # None on the keyed-window and sharded paths, which don't fuse
    raw_step: Optional[Callable] = None
    # the two halves of raw_step, exposed for the whole-app multi-query
    # optimizer (siddhi_tpu/optimizer): stage_body runs the pre-window
    # chain + window (shared once per merge group), select_body runs the
    # post-chain + selector over the window's output rows (stacked per
    # member).  raw_step == stage_body ∘ select_body by construction.
    stage_body: Optional[Callable] = None
    select_body: Optional[Callable] = None

    def describe(self) -> Dict:
        """Compiled-plan facts for EXPLAIN (observability/explain.py):
        what the planner chose — window processor, capacities, slot
        spaces, sharding — beyond what the query AST shows."""
        d: Dict[str, Any] = {
            "input_stream": self.input_stream_id,
            "batch_capacity": self.batch_capacity,
            "window_processor": type(self.window).__name__,
            "needs_timer": self.needs_timer,
            "in_columns": list(self.in_schema.names),
            "out_columns": list(self.out_schema.names),
        }
        if self.slot_allocator is not None:
            d["group_slot_capacity"] = self.slot_allocator.capacity
        if self.keyed_window:
            d["keyed_window"] = True
            d["key_capacity"] = self.key_capacity
        if self.partition_key_fn is not None:
            d["range_partition"] = True
        if self.pair_allocs:
            d["distinct_pair_slots"] = [a.capacity
                                        for a, _ in self.pair_allocs]
        if self.mesh is not None or self.keyed_mesh is not None:
            m = self.mesh or self.keyed_mesh
            d["sharded_over_devices"] = int(m.devices.size)
        if self.in_deps:
            d["table_probes"] = list(self.in_deps)
        # @serve (serving/): timer-bearing windows deliver inline so wake
        # scheduling stays synchronous — same exclusion as @pipeline
        d["serve_eligible"] = not self.needs_timer
        return d


def _env_for(scope_key: str, cols, ts):
    return {scope_key: cols, "__ts__": ts}


def _apply_chain(chain, env, sid, cols, keep, data_row):
    """Run a filter/stream-fn handler chain over columnar rows.  Filters
    only gate `data_row` rows (TIMER/RESET pass through untouched)."""
    for entry in chain:
        if entry[0] == "filter":
            m = entry[1].fn(env)
            keep = jnp.logical_and(
                keep, jnp.logical_or(jnp.logical_not(data_row), m))
        else:
            _, dtypes, fn = entry
            new_cols, keep = fn(env, keep)
            cols = cols + tuple(
                jnp.asarray(c, d) for c, d in zip(new_cols, dtypes))
            env[sid] = cols
    return env, cols, keep


def _merge_rows(ovalid, col):
    """Merge row-aligned per-device outputs: each row is valid on exactly
    one device, so zero-the-rest + psum reconstructs the global row."""
    from jax import lax
    z = jnp.where(ovalid, col, jnp.zeros_like(col))
    if col.dtype == jnp.bool_:
        return lax.psum(z.astype(jnp.int32), "shard") > 0
    return lax.psum(z, "shard")


def _shard_plain_step(step, mesh, sel, wproc, group_slots: int,
                      owner=None):
    """Shard a windowless partitioned group-by step over the mesh.

    Design (same scaling-book recipe as the pattern path): group slots are
    the shard axis — each device owns a G/n block of every accumulator
    slab.  Event rows replicate to all devices; each device masks `valid`
    to the rows whose slot falls in its block and runs the unmodified
    single-device body over local slot ids.  Groups are independent, so
    the data path needs no communication; output rows (each owned by
    exactly one device) merge with psum, the wake scalar with pmin.
    This scales group capacity and segment-op work G/n per chip — the
    reference's thread-per-Disruptor scale-up becomes SPMD scale-out
    (CORE/stream/StreamJunction.java:296)."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    n = mesh.devices.size
    blk = group_slots // n

    ex_w = wproc.init_state()
    ex_s = sel.init_state()
    wspec = jax.tree.map(lambda x: P(), ex_w)     # NoWindow state: scalars
    sspec = jax.tree.map(lambda x: P("shard"), ex_s)
    rspec = P()                                   # event rows: replicated

    def local(state, ts, kind, valid, cols, gslot, now, in_tabs, pslots):
        dev = lax.axis_index("shard")
        ts = pcast(ts, ("shard",), to="varying")
        kind = pcast(kind, ("shard",), to="varying")
        valid = pcast(valid, ("shard",), to="varying")
        cols = tuple(pcast(c, ("shard",), to="varying") for c in cols)
        gslot = pcast(gslot, ("shard",), to="varying")
        in_tabs = jax.tree.map(
            lambda x: pcast(x, ("shard",), to="varying"), in_tabs)
        wstate, astate = state
        old_w = wstate
        wstate = jax.tree.map(
            lambda x: pcast(x, ("shard",), to="varying"), wstate)
        # round-robin ownership (slot % n): sequential slot allocation
        # would park every early group on device 0 under a block split —
        # same layout as the pattern path, device column = (s%n)*blk + s//n
        owned = (gslot % n) == dev
        local_slot = jnp.where(owned, gslot // n, 0)
        lvalid = jnp.logical_and(valid, owned)
        (wstate, astate), (ots, okind, ovalid, ocols), wake = step(
            (wstate, astate), ts, kind, lvalid, cols, local_slot, now,
            in_tabs, pslots)
        # outputs stay ROW-ALIGNED to the input batch (NoWindow.compact is
        # off on this path), so each row is valid on exactly its owner
        # device and a psum merge preserves single-device delivery order
        ots = _merge_rows(ovalid, ots)
        okind = _merge_rows(ovalid, okind)
        ocols = tuple(_merge_rows(ovalid, c) for c in ocols)
        ovalid = lax.psum(ovalid.astype(jnp.int32), "shard") > 0
        wake = lax.pmin(wake, "shard")
        # NoWindow's state is the additive seq counter: re-replicate as
        # old + sum of per-device deltas (pattern-path recipe)
        wstate = jax.tree.map(
            lambda old, new: old + lax.psum(
                new - pcast(old, ("shard",), to="varying"), "shard"),
            old_w, wstate)
        return (wstate, astate), (ots, okind, ovalid, ocols), wake

    sharded = shard_map(
        local, mesh=mesh,
        in_specs=((wspec, sspec), rspec, rspec, rspec, rspec, rspec, P(),
                  rspec, rspec),
        out_specs=((wspec, sspec), (P(), P(), P(), P()), P()))
    return jit_step(sharded, owner=owner, donate_argnums=(0,))


def _shard_keyed_step(kstep, mesh, K: int, owner=None):
    """Shard the keyed-window step over the mesh 'shard' axis.

    Partition keys are the shard axis: each device owns the window-state
    rows of keys with `key_idx % n == dev` (round-robin — sequential key
    allocation would park early keys on device 0), stored at local row
    key_idx // n. Event rows and the [Kb, E] per-key grouping replicate;
    non-owned keys turn into pad rows (sentinel K) whose window writes
    drop and whose output rows invalidate. Selector accumulators stay
    REPLICATED (group slots interleave keys arbitrarily, so they cannot
    share the key layout); each group slot is written by exactly one
    device per batch, so states merge exactly with a changed-delta psum.
    Outputs stay row-aligned — the psum merge preserves single-device
    delivery order. Wake scalars ride pmin."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    n = mesh.devices.size

    def dmerge(old, new):
        """Exact merge when at most one device changed each element.
        `old` must be the replicated (unvaried) input so old + psum(delta)
        is statically replicated."""
        is_bool = old.dtype == jnp.bool_
        oi = old.astype(jnp.int32) if is_bool else old
        ni = new.astype(jnp.int32) if is_bool else new
        oi_v = pcast(oi, ("shard",), to="varying")
        changed = ni != oi_v
        merged = oi + lax.psum(
            jnp.where(changed, ni - oi_v, jnp.zeros_like(ni)), "shard")
        return merged.astype(jnp.bool_) if is_bool else merged

    def local(state, ts, kind, valid, cols, gslot, key_idx, sel_idx, now,
              in_tabs):
        dev = lax.axis_index("shard")
        vary = lambda x: pcast(x, ("shard",), to="varying")  # noqa: E731
        ts, kind, valid, gslot = vary(ts), vary(kind), vary(valid), \
            vary(gslot)
        cols = tuple(vary(c) for c in cols)
        key_idx, sel_idx = vary(key_idx), vary(sel_idx)
        in_tabs = jax.tree.map(vary, in_tabs)
        wslab, astate = state
        old_a = astate
        astate = jax.tree.map(vary, astate)
        # host pad rows carry sentinel key_idx == K: they must stay pads on
        # EVERY device (K % n would otherwise claim them as a real key)
        owned = jnp.logical_and((key_idx % n) == dev, key_idx < K)
        key_l = jnp.where(owned, key_idx // n, K)   # K == drop sentinel
        (wslab, astate), (ots, okind, ovalid, ocols), wake = kstep(
            (wslab, astate), ts, kind, valid, cols, gslot, key_l, sel_idx,
            now, in_tabs)
        ots = _merge_rows(ovalid, ots)
        okind = _merge_rows(ovalid, okind)
        ocols = tuple(_merge_rows(ovalid, c) for c in ocols)
        ovalid = lax.psum(ovalid.astype(jnp.int32), "shard") > 0
        wake = lax.pmin(wake, "shard")
        astate = jax.tree.map(dmerge, old_a, astate)
        return (wslab, astate), (ots, okind, ovalid, ocols), wake

    wspec = P("shard")
    rspec = P()
    sharded = shard_map(
        local, mesh=mesh,
        in_specs=((wspec, rspec), rspec, rspec, rspec, rspec, rspec, rspec,
                  rspec, P(), rspec),
        out_specs=((wspec, rspec), (P(), P(), P(), P()), P()))
    return jit_step(sharded, owner=owner, donate_argnums=(0,))


def plan_single_query(
    query: Query,
    name: str,
    definitions: Dict[str, StreamDefinition],
    schemas: Dict[str, ev.Schema],
    interner: ev.StringInterner,
    batch_capacity: int = 512,
    group_slots: int = 4096,
    window_capacity_hint: int = 2048,
    partition_positions: Optional[List[int]] = None,
    partition_key_fn: Optional[Callable] = None,
    window_key_allocator: Optional[SlotAllocator] = None,
    key_capacity: int = 0,
    named_window_input: bool = False,
    config_manager=None,
    script_functions=None,
    mesh=None,
) -> PlannedQuery:
    ist = query.input_stream
    assert isinstance(ist, SingleInputStream)
    sid = ist.unique_stream_id
    if sid not in schemas:
        raise CompileError(f"undefined stream {sid!r}")
    in_schema = schemas[sid]

    # `in` operator table dependencies (reference: InConditionExpressionExecutor)
    from ..query_api.expression import In as _In, walk as _walk
    in_deps: List[str] = []
    def _scan_in(e):
        for node in _walk(e):
            if isinstance(node, _In) and node.source_id not in in_deps:
                in_deps.append(node.source_id)
    for h in ist.stream_handlers:
        if isinstance(h, Filter):
            _scan_in(h.expression)
    for oa in query.selector.selection_list:
        _scan_in(oa.expression)
    if query.selector.having_expression is not None:
        _scan_in(query.selector.having_expression)

    scope = Scope()
    scope.interner = interner
    scope.add_source(sid, in_schema, alias=ist.stream_reference_id)
    # extensions read per-extension config via
    # scope.config_manager.generate_config_reader(namespace, name)
    # (reference: ConfigReader wired in SingleInputStreamParser :205-217)
    scope.config_manager = config_manager
    scope.script_functions = script_functions

    # ---- handlers: filters/stream-functions before/after the window --------
    # chain entries: ('filter', compiled) | ('fn', dtypes, fn)
    pre_chain, post_chain = [], []
    if named_window_input:
        from .window import PassAllWindow
        window_proc: WindowProcessor = PassAllWindow(
            in_schema, [], batch_capacity)
    else:
        window_proc = NoWindow(in_schema, [], batch_capacity)
    seen_window = False
    chain_schema = in_schema   # grows as stream functions append attributes
    for h in ist.stream_handlers:
        if isinstance(h, Filter):
            c = compile_expression(h.expression, scope)
            if c.type != "BOOL":
                raise CompileError("filter expression must be boolean")
            (post_chain if seen_window else pre_chain).append(("filter", c))
        elif isinstance(h, Window):
            if named_window_input:
                raise CompileError(
                    "cannot apply a window to a named-window input")
            if seen_window:
                raise CompileError("only one window per input stream")
            seen_window = True
            window_proc = create_window(
                (h.namespace + ":" if h.namespace else "") + h.name,
                chain_schema, h.parameters, batch_capacity,
                capacity_hint=window_capacity_hint)
        elif isinstance(h, StreamFunction):
            from .streamfn import STREAM_FUNCTIONS
            fname = (h.namespace + ":" if h.namespace else "") + h.name
            sfn = STREAM_FUNCTIONS.get(fname)
            if sfn is None:
                raise CompileError(
                    f"unknown stream function {fname!r}; registered: "
                    f"{sorted(STREAM_FUNCTIONS)}")
            names, types, fn = sfn.compile(h.parameters, scope, sid)
            if names:
                sdef = StreamDefinition(sid)
                for a in chain_schema.definition.attribute_list:
                    sdef.attribute(a.name, a.type)
                for n, t in zip(names, types):
                    sdef.attribute(n, t)
                chain_schema = ev.Schema(sdef, interner,
                                         objects=in_schema.objects)
                scope.add_source(sid, chain_schema,
                                 alias=ist.stream_reference_id,
                                 default=False)
            dtypes = [ev.dtype_of(t) for t in types]
            (post_chain if seen_window else pre_chain).append(
                ("fn", dtypes, fn))

    # ---- selector -----------------------------------------------------------
    out_target = query.output_stream.target_id if query.output_stream else ""
    sel = SelectorExec(query.selector, scope, chain_schema, group_slots,
                       out_target or name, interner)

    # output schema
    out_def = StreamDefinition(out_target or f"#{name}.out")
    for n, t in zip(sel.out_names, sel.out_types):
        out_def.attribute(n, t)
    out_schema = ev.Schema(out_def, interner, objects=in_schema.objects)

    # group-by slot allocation (host side).  Inside a partition, the
    # partition key is prepended to the group key: state isolation per
    # partition key composes with group-by
    # (reference: PartitionStateHolder's nested partitionKey->groupByKey map)
    gpos = list(sel.group_by_positions)
    if any(p >= len(in_schema.names) for p in gpos):
        raise CompileError(
            "group by on stream-function-appended attributes is not yet "
            "supported")
    keyed_window = bool(
        (partition_positions or partition_key_fn) and seen_window)
    window_key_positions = list(partition_positions or [])
    skey_pos = getattr(window_proc, "session_key_pos", None)
    if skey_pos is not None:
        # session(gap, key): standalone keyed window — the session key
        # scopes the window slab exactly like a partition key would
        # (reference: SessionWindowProcessor.java sessionKey overload)
        if partition_positions or partition_key_fn:
            raise CompileError(
                "session(gap, key) inside `partition with` is redundant: "
                "the partition key already scopes the session window")
        if skey_pos >= len(in_schema.names):
            # key slots resolve on raw staged columns; appended attributes
            # don't exist there (same bound as the group-by guard above)
            raise CompileError(
                "session key on stream-function-appended attributes is "
                "not yet supported")
        keyed_window = True
        window_key_positions = [skey_pos]
    if keyed_window and (window_key_allocator is None or key_capacity <= 0):
        raise CompileError(
            "windows inside partitions (and session(gap, key) queries) "
            "need a key allocator" if skey_pos is None else
            "internal: session-key query planned without its key "
            "allocator (runtime wiring bug)")
    if partition_positions:
        if sel.has_aggregation or gpos:
            gpos = [p for p in partition_positions if p not in gpos] + gpos
    needs_alloc = bool(gpos) or (
        partition_key_fn is not None and (sel.has_aggregation or gpos))
    allocator = SlotAllocator(group_slots, name=f"{name}:groupby") \
        if needs_alloc else None

    # distinctCount pair slots: (group, value) -> refcount slot
    pair_allocs: List[Tuple[SlotAllocator, int]] = []
    if sel.bank.pair_sources:
        if seen_window or keyed_window:
            raise CompileError(
                "distinctCount over windowed queries lands in a later "
                "phase (expired-row pair slots need buffer plumbing)")
        for j, v in enumerate(sel.bank.pair_sources):
            _, pos, _ = scope.resolve(v)
            if pos >= len(in_schema.names):
                raise CompileError(
                    "distinctCount on stream-function-appended attributes "
                    "is not yet supported")
            pair_allocs.append((SlotAllocator(
                sel.bank.K * 8, name=f"{name}:distinct{j}"), pos))

    out_event_type = (query.output_stream.output_event_type
                      if query.output_stream and
                      query.output_stream.output_event_type
                      else "CURRENT_EVENTS")

    # ---- the fused step -----------------------------------------------------
    wproc = window_proc

    def _probe_env(in_tabs):
        """`x in Table` probe closures for this query's table deps —
        pure functions of the snapshot columns, rebuilt identically in
        both step halves."""
        env = {}
        for dep, (tcol0, tvalid) in zip(in_deps, in_tabs):
            def probe(vals, _tc=tcol0, _tv=tvalid):
                return jnp.any(jnp.logical_and(
                    vals[:, None] == _tc[None, :], _tv[None, :]), axis=1)
            env["__in__:" + dep] = probe
        return env

    def stage_body(wstate, ts, kind, valid, cols, gslot, now, in_tabs):
        """Pre-window chain + window advance: the half of the step a
        merge group shares (one buffer, staged once per dispatch)."""
        env = {sid: cols, "__ts__": ts, "__now__": now, "__kind__": kind}
        env.update(_probe_env(in_tabs))
        keep = valid
        is_current = kind == ev.CURRENT
        if named_window_input:
            # expired rows must pass the same filters so signed aggregation
            # stays balanced (reference: filter sits after the shared window)
            is_current = jnp.logical_or(is_current, kind == ev.EXPIRED)
        env, cols, keep = _apply_chain(pre_chain, env, sid, cols, keep,
                                       is_current)
        rows = Rows(ts=ts, kind=kind, valid=keep,
                    seq=jnp.zeros_like(ts), gslot=gslot, cols=cols)
        wstate, wout = wproc.process(wstate, rows, now)
        return wstate, wout.rows, wout.next_wakeup

    def select_body(astate, orows, now, in_tabs, pslots):
        """Post-window chain + selector over the window's output rows:
        the per-query half, stacked per member in a merged dispatch."""
        env2 = {sid: orows.cols, "__ts__": orows.ts, "__now__": now,
                "__kind__": orows.kind}
        env2.update(_probe_env(in_tabs))
        # distinctCount pair slots (unwindowed: orows is the input order)
        for j in range(len(pair_allocs)):
            env2[f"__pslot__{j}"] = pslots[j]
        if post_chain:
            data_row = jnp.logical_or(orows.kind == ev.CURRENT,
                                      orows.kind == ev.EXPIRED)
            env2, ocols, keep2 = _apply_chain(
                post_chain, env2, sid, orows.cols, orows.valid, data_row)
            orows = orows._replace(valid=keep2, cols=ocols)
        return sel.process(astate, orows, env2)

    def step(state, ts, kind, valid, cols, gslot, now, in_tabs=(),
             pslots=()):
        wstate, astate = state
        wstate, orows, wake = stage_body(wstate, ts, kind, valid, cols,
                                         gslot, now, in_tabs)
        astate, (ots, okind, ovalid, ocols) = select_body(
            astate, orows, now, in_tabs, pslots)
        return ((wstate, astate), (ots, okind, ovalid, ocols), wake)

    plain_mesh = None
    keyed_mesh = None
    raw_step = None
    if keyed_window:
        # ---- keyed window: one window state per partition key ------------
        # The window processor is a pure (state, rows, now) -> (state', out)
        # function, so per-key isolation is jax.vmap over a [K, ...] state
        # slab with events arranged [Kb, E] per key (same layout as the
        # pattern NFA path).  Reference semantics: each partition key owns a
        # private window instance (PartitionRuntimeImpl clone-per-key).
        K = key_capacity

        def kstep(state, ts, kind, valid, cols, gslot, key_idx, sel_idx,
                  now, in_tabs=()):
            wslab, astate = state
            env = {sid: cols, "__ts__": ts, "__now__": now,
                   "__kind__": kind}
            for dep, (tcol0, tvalid) in zip(in_deps, in_tabs):
                def probe(vals, _tc=tcol0, _tv=tvalid):
                    return jnp.any(jnp.logical_and(
                        vals[:, None] == _tc[None, :], _tv[None, :]),
                        axis=1)
                env["__in__:" + dep] = probe
            env, cols, keep = _apply_chain(pre_chain, env, sid, cols, valid,
                                           kind == ev.CURRENT)
            sidx = jnp.clip(sel_idx, 0)
            take = lambda a: a[sidx]                      # noqa: E731
            evalid = jnp.logical_and(sel_idx >= 0, take(keep))
            rows_k = Rows(ts=take(ts), kind=take(kind), valid=evalid,
                          seq=jnp.zeros_like(take(ts)), gslot=take(gslot),
                          cols=tuple(take(c) for c in cols))
            kidx = jnp.clip(key_idx, 0, K - 1)
            st_k = jax.tree.map(lambda x: x[kidx], wslab)
            st_k2, wout = jax.vmap(
                wproc.process, in_axes=(0, 0, None))(st_k, rows_k, now)
            # pad rows (key_idx == K) drop on scatter-back
            wslab = jax.tree.map(
                lambda s, n: s.at[key_idx].set(n, mode="drop"),
                wslab, st_k2)
            ork = wout.rows
            flat = lambda a: a.reshape((-1,) + a.shape[2:])  # noqa: E731
            pad_live = (key_idx < K)[:, None]
            orows = Rows(
                ts=flat(ork.ts), kind=flat(ork.kind),
                valid=flat(jnp.logical_and(ork.valid, pad_live)),
                seq=flat(ork.seq), gslot=flat(ork.gslot),
                cols=tuple(flat(c) for c in ork.cols))
            env2 = {sid: orows.cols, "__ts__": orows.ts, "__now__": now,
                    "__kind__": orows.kind}
            for k2, v2 in env.items():
                if k2.startswith("__in__:"):
                    env2[k2] = v2
            if post_chain:
                data_row = jnp.logical_or(orows.kind == ev.CURRENT,
                                          orows.kind == ev.EXPIRED)
                env2, ocols, keep2 = _apply_chain(
                    post_chain, env2, sid, orows.cols, orows.valid,
                    data_row)
                orows = orows._replace(valid=keep2, cols=ocols)
            astate, outs = sel.process(astate, orows, env2)
            return ((wslab, astate), outs, jnp.min(wout.next_wakeup))

        kshardable = (
            mesh is not None and mesh.devices.size > 1
            and K % mesh.devices.size == 0 and not pair_allocs
            and not sel._order_by and query.selector.limit is None
            and query.selector.offset is None
            and not getattr(wproc, "host_scheduled", False)
            # RESET-emitting batch windows reset ALL selector slots on any
            # device that sees the flush — multiple writers per slot break
            # the replicated-state delta merge; they stay single-device
            and not wproc.emits_reset)
        if kshardable:
            step_fn = _shard_keyed_step(kstep, mesh, K, owner=name)
            keyed_mesh = mesh
        else:
            step_fn = jit_step(kstep, owner=name, donate_argnums=(0,))
            keyed_mesh = None

        def init_state():
            single = wproc.init_state()
            slab = jax.tree.map(
                lambda x: jnp.array(jnp.broadcast_to(
                    jnp.asarray(x)[None],
                    (K,) + jnp.asarray(x).shape)), single)
            return (slab, sel.init_state())
    else:
        shardable = (
            mesh is not None and allocator is not None
            and isinstance(wproc, NoWindow) and not pair_allocs
            and not sel._order_by and query.selector.limit is None
            and query.selector.offset is None
            and allocator.capacity % mesh.devices.size == 0)
        if shardable:
            # keep outputs row-aligned so the sharded psum merge preserves
            # single-device delivery order
            wproc.compact = False
            step_fn = _shard_plain_step(step, mesh, sel, wproc,
                                        allocator.capacity, owner=name)
            plain_mesh = mesh
        else:
            step_fn = jit_step(step, owner=name, donate_argnums=(0,))
            plain_mesh = None
            raw_step = step

        def init_state():
            return (wproc.init_state(), sel.init_state())

    return PlannedQuery(
        name=name,
        input_stream_id=sid,
        in_schema=in_schema,
        out_schema=out_schema,
        output_target=out_target,
        output_event_type=out_event_type,
        window=wproc,
        group_by_positions=gpos,
        selector_exec=sel,
        step=step_fn,
        init_state=init_state,
        slot_allocator=allocator,
        batch_capacity=batch_capacity,
        needs_timer=wproc.needs_timer,
        in_deps=in_deps,
        partition_key_fn=partition_key_fn,
        keyed_window=keyed_window,
        window_key_allocator=window_key_allocator,
        window_key_positions=window_key_positions,
        key_capacity=key_capacity,
        pair_allocs=pair_allocs,
        mesh=plain_mesh,
        keyed_mesh=keyed_mesh,
        emits_uuid=scope.uses_uuid,
        raw_step=raw_step,
        stage_body=stage_body if raw_step is not None else None,
        select_body=select_body if raw_step is not None else None,
    )
