"""Typed exception hierarchy.

Reference (what): CORE/exception/* — ~20 typed exceptions rooted at
RuntimeException, each carrying query-context info where available
(e.g. SiddhiAppCreationException, ConnectionUnavailableException,
CannotRestoreSiddhiAppStateException).  TPU design (how): one Python
hierarchy rooted at SiddhiError; compile-time errors keep the line/column
context the tokenizer attaches, runtime errors name the query so fault
streams (@OnError) can route them.
"""
from __future__ import annotations


class SiddhiError(Exception):
    """Root of the framework's exception hierarchy."""


# -- compile time -------------------------------------------------------------
class CompileError(SiddhiError):
    """Expression/query cannot be compiled to a device function
    (reference: SiddhiAppCreationException)."""


class SiddhiParserException(CompileError):
    """SiddhiQL text failed to parse (reference:
    QC/exception/SiddhiParserException)."""


class SiddhiAppValidationError(CompileError):
    """App-level semantic validation failed (reference:
    SiddhiAppValidationException)."""


class DuplicateDefinitionError(CompileError):
    """Two definitions share an id (reference:
    DuplicateDefinitionException)."""


class DefinitionNotExistError(CompileError, KeyError):
    """A query references an undefined stream/table/window/aggregation
    (reference: DefinitionNotExistException).  Subclasses KeyError for
    backward compatibility with callers catching the untyped lookup error."""


class OperationNotSupportedError(CompileError):
    """Valid SiddhiQL that this engine does not (yet) execute (reference:
    OperationNotSupportedException)."""


# -- runtime ------------------------------------------------------------------
class SiddhiAppRuntimeError(SiddhiError):
    """Event-processing failure inside a running app (reference:
    SiddhiAppRuntimeException)."""


class QueryNotExistError(SiddhiError, KeyError):
    """Callback/on-demand query addressed a query id that is not part of
    the app (reference: QueryNotExistException).  Subclasses KeyError for
    backward compatibility with callers catching the untyped lookup error."""


class MatchOverflowError(SiddhiAppRuntimeError):
    """Pattern matches exceeded the implicit per-key emission capacity; the
    batch would silently lose rows.  Set @emit(rows='N') to raise the cap
    or explicitly accept capped delivery."""


class CapacityExceededError(SiddhiAppRuntimeError, RuntimeError):
    """A fixed-capacity state slab (key slots, window rows) is full.
    Subclasses RuntimeError for backward compatibility with callers that
    caught the untyped error."""


class AdmissionDeniedError(SiddhiError):
    """The admission controller (core/admission.py) refused the request:
    a deploy whose static state estimate exceeds the configured memory
    ceiling, or an ingest send that exhausted its `block` deadline.
    `components` carries the per-component byte breakdown for memory
    denials (the same breakdown lint MEM001 cites), empty otherwise."""

    def __init__(self, message: str, components=None):
        super().__init__(message)
        self.components = dict(components or {})


class OnDemandQueryCreationError(CompileError):
    """On-demand (store) query failed to compile (reference:
    OnDemandQueryCreationException)."""


# -- persistence --------------------------------------------------------------
class PersistenceError(SiddhiError):
    """Snapshot persist failed (reference: PersistenceStoreException)."""


class NoPersistenceStoreError(PersistenceError):
    """persist() called with no PersistenceStore configured (reference:
    NoPersistenceStoreException)."""


class CannotRestoreStateError(PersistenceError):
    """Snapshot restore failed or revision missing (reference:
    CannotRestoreSiddhiAppStateException)."""


class CorruptSnapshotError(PersistenceError):
    """A stored snapshot failed its CRC32 integrity check (torn write,
    truncation, or bit rot).  restore_last_revision() treats this as
    "skip to the previous good revision", never as fatal."""


# -- I/O ----------------------------------------------------------------------
class ConnectionUnavailableError(SiddhiError):
    """Source/sink/store backing system unreachable (reference:
    CORE/exception/ConnectionUnavailableException).  Transports raise
    THIS (not bare OSError/ValueError) for connectivity failures so the
    resilience layer (io/resilience.py) can distinguish a retryable
    transport outage from an application bug."""


# historical name, kept importable: pre-resilience code and extensions
# caught the Java-style spelling
ConnectionUnavailableException = ConnectionUnavailableError


class MappingFailedError(SiddhiAppRuntimeError):
    """Source/sink mapper could not convert a payload (reference:
    MappingFailedException)."""
