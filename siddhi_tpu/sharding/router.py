"""Key-space router: the single source of truth for how partition keys
map onto a device mesh.

Reference (what): SiddhiQL's `partition with (key of Stream)` declares a
key-scoped state clone per partition key (CORE/partition/
PartitionRuntimeImpl.java:75).  TPU design (how): keys become an explicit
state axis distributed over the mesh's `shard` axis.  Three places used
to hand-roll the same layout arithmetic — the pattern runtime's staging
grouping, the partition purger's reset remap, and the dirty-mask marking
for incremental snapshots — and snapshot/restore could not move state
between mesh sizes at all because no one owned the mapping.  This module
owns it:

- **shard assignment** is round-robin on the allocator slot
  (`slot % n_shards`), so sequential slot allocation spreads early keys
  across devices instead of parking them all on device 0;
- **state row** of slot `s` on an `n`-way mesh of capacity `C` is
  `(s % n) * (C // n) + s // n`: device `s % n` owns the contiguous
  global block `[d*C/n, (d+1)*C/n)` and stores the key at local row
  `s // n` — exactly the layout `jax.sharding.PartitionSpec('shard')`
  splits;
- **re-bucketing** between mesh sizes is therefore a pure permutation of
  state rows (`rebucket_index`), which is what lets a snapshot taken on
  an N-way mesh restore onto an M-way mesh (core/runtime.restore).

The allocator slot a key resolves to is mesh-independent (keyslots
hashes key bytes, not devices), so the key->slot binding in a snapshot
is portable across mesh sizes as-is; only the slot->state-row layout
changes, and that is this router's job.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class ShardRouter:
    """Layout arithmetic + staging-time grouping for one key space
    (`capacity` slots) over `n_shards` devices.  `capacity` must divide
    evenly — the planner rounds key capacities up to a mesh multiple at
    wiring time (runtime._add_partition)."""

    __slots__ = ("n_shards", "capacity", "block")

    def __init__(self, n_shards: int, capacity: int):
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        if capacity % n_shards != 0:
            raise ValueError(
                f"key capacity {capacity} is not divisible by "
                f"{n_shards} shards")
        self.n_shards = int(n_shards)
        self.capacity = int(capacity)
        self.block = self.capacity // self.n_shards

    # -- layout ---------------------------------------------------------------
    def shard_of(self, slots: np.ndarray) -> np.ndarray:
        """Mesh shard owning each allocator slot (round-robin)."""
        return np.asarray(slots) % self.n_shards

    def local_of(self, slots: np.ndarray) -> np.ndarray:
        """Local state row of each slot on its owning shard."""
        return np.asarray(slots) // self.n_shards

    def state_row(self, slots: np.ndarray) -> np.ndarray:
        """Global state row of each allocator slot under the sharded
        layout (the row PartitionSpec('shard') places on shard
        `slot % n`)."""
        s = np.asarray(slots)
        return (s % self.n_shards) * self.block + s // self.n_shards

    def slot_of_row(self, rows: np.ndarray) -> np.ndarray:
        """Inverse of state_row: the allocator slot stored at each global
        state row."""
        r = np.asarray(rows)
        return (r % self.block) * self.n_shards + r // self.block

    def rebucket_index(self, old: "ShardRouter") -> np.ndarray:
        """Permutation `src` moving key state between mesh layouts:
        `new_state[..., j] = old_state[..., src[j]]` for every global
        state row j.  Both routers must cover the same slot capacity."""
        if old.capacity != self.capacity:
            raise ValueError(
                f"cannot re-bucket between capacities {old.capacity} "
                f"and {self.capacity}")
        rows = np.arange(self.capacity, dtype=np.int64)
        return old.state_row(self.slot_of_row(rows))

    # -- staging-time grouping ------------------------------------------------
    def group(self, slots: np.ndarray, valid: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Arrange a batch's resolved slots into the sharded device
        layout: (key_idx [n, Kb] int32 local rows, sel [n, Kb, E] int32
        batch indices (-1 = padding), counts [n] int64 events routed to
        each shard).  Pad rows carry local sentinel `block` — the device
        scatter-back drops them as out-of-bounds (keyslots layout
        contract)."""
        from ..core.keyslots import group_events_by_key
        n = self.n_shards
        slots = np.asarray(slots)
        shard = self.shard_of(slots)
        local = self.local_of(slots)
        groups: List[Tuple] = []
        counts = np.zeros(n, np.int64)
        for d in range(n):
            mask = (shard == d) & valid & (slots >= 0)
            counts[d] = int(mask.sum())
            groups.append(group_events_by_key(
                np.where(mask, local, -1), mask, pad=self.block))
        Kb = max(g[0].shape[0] for g in groups)
        E = max(g[1].shape[1] for g in groups)
        key_idx = np.full((n, Kb), self.block, np.int32)
        sel = np.full((n, Kb, E), -1, np.int32)
        for d, (ki, s, _kv) in enumerate(groups):
            key_idx[d, :ki.shape[0]] = ki
            sel[d, :s.shape[0], :s.shape[1]] = s
        return key_idx, sel, counts


# ---------------------------------------------------------------------------
# resolved accessors: the ONE place that maps a query runtime onto its
# mesh / key layout (consolidates the former getattr(.., "mesh"/
# "keyed_mesh", None) call sites across runtime/purger/aggregation)
# ---------------------------------------------------------------------------

def mesh_of(qr):
    """The plain/pattern shard mesh a query runtime executes under, or
    None (reads the compiled plan — the same field the step functions
    were built from)."""
    return getattr(getattr(qr, "planned", qr), "mesh", None)


def keyed_mesh_of(qr):
    """The keyed-window shard mesh, or None."""
    return getattr(getattr(qr, "planned", qr), "keyed_mesh", None)


def shard_count(obj) -> int:
    """Devices in an app runtime's / mesh's shard axis (1 = unsharded)."""
    mesh = getattr(obj, "mesh", obj)
    if mesh is None:
        return 1
    devs = getattr(mesh, "devices", None)
    return int(devs.size) if devs is not None else 1


def router_for(qr) -> Optional[ShardRouter]:
    """ShardRouter of a query runtime's key-distributed state, or None
    when the query's state carries no sharded key axis (single-device
    plans, joins — whose buffers ride GSPMD row sharding with no key
    layout)."""
    p = getattr(qr, "planned", None)
    if p is None:
        return None
    mesh = mesh_of(qr)
    if isinstance(getattr(p, "steps", None), dict):     # pattern plan
        if not getattr(p, "partition_positions", None) or mesh is None:
            return None
        return ShardRouter(shard_count(mesh), int(p.key_capacity))
    kmesh = keyed_mesh_of(qr)
    if kmesh is not None and getattr(p, "keyed_window", False):
        return ShardRouter(shard_count(kmesh), int(p.key_capacity))
    if mesh is not None and getattr(p, "slot_allocator", None) is not None:
        return ShardRouter(shard_count(mesh),
                           int(p.slot_allocator.capacity))
    return None


def group_router_for(qr) -> Optional[ShardRouter]:
    """Router of a plain query's GROUP-SLOT space (the selector slabs a
    windowless sharded group-by distributes), or None when those slabs
    are replicated — distinct from router_for, which resolves the KEY
    space (a keyed-window query has both: a sharded key slab and
    replicated selector state)."""
    p = getattr(qr, "planned", None)
    mesh = mesh_of(qr)
    if p is None or mesh is None or \
            isinstance(getattr(p, "steps", None), dict) or \
            getattr(p, "slot_allocator", None) is None:
        return None
    return ShardRouter(shard_count(mesh), int(p.slot_allocator.capacity))


def split_columns(cols: Sequence[np.ndarray], shard: np.ndarray,
                  n: int) -> List[List[np.ndarray]]:
    """Per-shard column split of a staged batch (diagnostics / per-shard
    snapshot export): returns n lists of column arrays."""
    return [[np.asarray(c)[shard == d] for c in cols] for d in range(n)]
