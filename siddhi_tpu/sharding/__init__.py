"""Sharded serving runtime: key-partitioned multi-chip execution behind
the normal InputHandler/source/sink API.

The package owns three concerns that used to be scattered or missing:

- `router` — the canonical key->shard/state-row layout (staging-time
  grouping, purger resets, dirty-mask marking all route through it);
- `snapshot` — mesh-resize re-bucketing so a snapshot taken on an N-way
  mesh restores onto an M-way mesh (including M=1);
- `metrics` — per-shard state-bytes/balance accounting for /metrics,
  /healthz, and EXPLAIN's sharding node.

Entry point for serving stays `SiddhiManager.create_siddhi_app_runtime
(app, mesh=Mesh(devices, ('shard',)))`: the runtime then routes every
ingest path (sync, @async, @pipeline, @fuse) across the mesh with output
byte-identical to the unsharded runtime.
"""
from .router import (ShardRouter, group_router_for,  # noqa: F401
                     keyed_mesh_of, mesh_of, router_for, shard_count)
from .snapshot import (needs_rebucket, query_layout,  # noqa: F401
                       rebucket_rows, rebucket_selector, rebucket_state)
from .metrics import (explain_node, shard_report,  # noqa: F401
                      shard_state_bytes, step_collectives)
