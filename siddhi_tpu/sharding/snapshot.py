"""Mesh-resize snapshot re-bucketing: key state moves between mesh sizes
as a pure permutation of state rows.

Reference (what): the reference's PersistenceStore snapshots are
layout-free object graphs — a restored app re-hydrates per-key state
maps whatever the thread count.  TPU design (how): our per-key state is
dense `[..., K]` slabs whose row order IS the mesh layout (`slot s` at
row `(s % n) * (K/n) + s // n`, sharding/router.py), so a snapshot taken
on an N-way mesh holds rows in N-way order and restoring it verbatim
onto an M-way mesh would scatter every key's state onto the wrong
device.  Each query snapshot therefore records its `layout`
(kind + shard count + capacity); restore compares it against the target
runtime's layout and permutes the key axis through
`ShardRouter.rebucket_index` — key->slot bindings are mesh-independent
(keyslots hashes key bytes), so the slot maps restore unchanged and only
the slot->row order moves.

Three state families carry a key-ordered axis:

- **pattern** (partitioned NFA): packed blobs `b32/b64 [W, K]` (key axis
  1) plus selector accumulator slabs `[K, ...]` (key axis 0 — sharded
  patterns shard the selector with the same layout, see
  pattern_planner._shard_step's sspec);
- **plain** (windowless partitioned group-by): selector slabs
  `[G, ...]` over the group-slot space;
- **keyed** (windows inside partitions / session(gap, key)): the window
  state slab `[K, ...]`; its selector state stays replicated
  (planner._shard_keyed_step) and needs no permutation.

Join buffers ride GSPMD axis-0 row sharding with no key layout — a
restored join re-places through JoinQueryRuntime.place_state and needs
no re-bucketing (layout None).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .router import ShardRouter, keyed_mesh_of, mesh_of, shard_count


def query_layout(qr) -> Optional[Dict[str, Any]]:
    """The key-state layout a query runtime's snapshot is written in:
    {'kind': 'pattern'|'plain'|'keyed', 'n': shards, 'capacity': rows},
    or None when the state has no key-ordered axis (single-key patterns,
    joins, unkeyed plain queries)."""
    p = getattr(qr, "planned", None)
    if p is None:
        return None
    if isinstance(getattr(p, "steps", None), dict):     # pattern plan
        if not getattr(p, "partition_positions", None):
            return None
        return {"kind": "pattern", "n": shard_count(mesh_of(qr)),
                "capacity": int(p.key_capacity)}
    if hasattr(p, "step_left"):                          # join plan
        return None
    if getattr(p, "keyed_window", False):
        return {"kind": "keyed", "n": shard_count(keyed_mesh_of(qr)),
                "capacity": int(p.key_capacity)}
    if getattr(p, "slot_allocator", None) is not None:
        # n=1 for unsharded group-bys: the identity layout — recorded so
        # a snapshot from a SHARDED runtime re-buckets when restoring
        # onto an unsharded one (and vice versa)
        return {"kind": "plain", "n": shard_count(mesh_of(qr)),
                "capacity": int(p.slot_allocator.capacity)}
    return None


def needs_rebucket(old: Optional[Dict], new: Optional[Dict]) -> bool:
    """True when a snapshot written under `old` must be permuted to load
    into a runtime laid out as `new`.  Missing layouts (pre-round-8
    snapshots, or an unkeyed target) mean "restore verbatim" — exactly
    the old behavior."""
    if old is None or new is None:
        return False
    return int(old.get("n", 1)) != int(new.get("n", 1)) and \
        old.get("capacity") == new.get("capacity") and \
        old.get("kind") == new.get("kind")


def _perm(old: Dict, new: Dict) -> np.ndarray:
    cap = int(new["capacity"])
    return ShardRouter(int(new["n"]), cap).rebucket_index(
        ShardRouter(int(old["n"]), cap))


def _take(arr, src: np.ndarray, axis: int):
    a = np.asarray(arr)
    if a.ndim <= axis or a.shape[axis] != src.shape[0]:
        return arr
    return np.take(a, src, axis=axis)


def _sel_specs(planned):
    sel = getattr(planned, "selector_exec", None)
    bank = getattr(sel, "bank", None)
    return getattr(bank, "specs", None)


def _permute_selector(sel_state, specs, src: np.ndarray):
    """Permute slot-indexed selector slabs; leaves in a different slot
    space (pair refcounts via slot_src) or of a different length pass
    through untouched — same discrimination the partition purger's reset
    applies (runtime._reset_pattern_keys / _reset_selector_slots)."""
    if specs is None or len(specs) != len(sel_state):
        return sel_state
    return tuple(
        a if getattr(s, "slot_src", None) is not None
        else _take(a, src, 0)
        for a, s in zip(sel_state, specs))


def rebucket_state(host_state, old: Dict, new: Dict, planned):
    """Permute a host (numpy) query-state snapshot from mesh layout `old`
    into `new`.  Returns the state unchanged when the shapes don't match
    the declared layout (defensive: a mismatched snapshot fails later on
    upload exactly as it always did)."""
    src = _perm(old, new)
    kind = new["kind"]
    try:
        if kind == "pattern":
            (b32, b64, scalars), sel_state = host_state
            b32 = _take(b32, src, 1)
            b64 = _take(b64, src, 1)
            sel_state = _permute_selector(sel_state, _sel_specs(planned),
                                          src)
            return ((b32, b64, scalars), sel_state)
        if kind == "plain":
            wstate, astate = host_state
            astate = _permute_selector(astate, _sel_specs(planned), src)
            return (wstate, astate)
        if kind == "keyed":
            import jax
            wslab, astate = host_state
            wslab = jax.tree.map(lambda a: _take(a, src, 0), wslab)
            return (wslab, astate)
    except Exception:  # noqa: BLE001 — fall through to verbatim restore
        pass
    return host_state


def rebucket_selector(sel_state, old: Dict, new: Dict, planned):
    """Permute just a selector-state tuple between layouts (incremental
    pattern deltas ship the full selector tree next to per-row state
    columns)."""
    try:
        return _permute_selector(sel_state, _sel_specs(planned),
                                 _perm(old, new))
    except Exception:  # noqa: BLE001 — fall through to verbatim restore
        return sel_state


def rebucket_rows(rows: np.ndarray, old: Dict, new: Dict) -> np.ndarray:
    """Map state-ROW indices recorded under layout `old` (incremental
    snapshots store dirty rows, not slots) onto layout `new`."""
    cap = int(new["capacity"])
    old_r = ShardRouter(int(old["n"]), cap)
    new_r = ShardRouter(int(new["n"]), cap)
    return new_r.state_row(old_r.slot_of_row(np.asarray(rows)))
