"""Shard-aware observability: per-shard state bytes, routing balance,
and collective-op introspection.

Scrape-path invariant (same as observability/exposition.py): everything
here reads host-side metadata only — `leaf.sharding.shard_shape` is
layout arithmetic, never a device fetch — so /metrics and /healthz stay
device-silent on sharded apps too.  The one exception,
`step_collectives`, compiles a step's HLO to list its collectives; it is
called only from EXPLAIN's deep mode (an on-demand diagnostic, never the
scrape path).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..observability.memory import leaf_nbytes
from .router import router_for, shard_count

# collective-op HLO tokens asserted by dryrun_multichip and reported by
# EXPLAIN's sharding node — one list, two consumers
COLLECTIVE_TOKENS = ("all-gather", "all-reduce", "collective-permute",
                     "all-to-all", "reduce-scatter")


def _leaf_shard_bytes(leaf) -> int:
    """Bytes of one leaf RESIDENT PER DEVICE: sharded leaves report their
    shard slice, replicated leaves (and host numpy) their full size."""
    nb = leaf_nbytes(leaf)
    sh = getattr(leaf, "sharding", None)
    shape = getattr(leaf, "shape", None)
    if sh is None or shape is None:
        return nb
    try:
        per = 1
        for d in sh.shard_shape(tuple(shape)):
            per *= int(d)
        return per * int(np.dtype(leaf.dtype).itemsize)
    except Exception:  # noqa: BLE001 — metrics must not throw
        return nb


def tree_shard_bytes(tree) -> int:
    try:
        import jax
        return sum(_leaf_shard_bytes(leaf)
                   for leaf in jax.tree_util.tree_leaves(tree))
    except Exception:  # noqa: BLE001 — metrics must not throw
        return 0


def shard_state_bytes(rt) -> Dict[int, int]:
    """{shard index: resident state bytes} for one app runtime.  The
    layout is uniform by construction (PartitionSpec splits evenly), so
    every shard reports the same residency — the value operators watch is
    that it stays ~1/n of the unsharded total as the mesh grows."""
    n = shard_count(rt)
    if n < 2:
        return {}
    per = 0
    for qr in getattr(rt, "query_runtimes", {}).values():
        per += tree_shard_bytes(getattr(qr, "state", None))
    for nw in getattr(rt, "named_windows", {}).values():
        per += tree_shard_bytes(getattr(nw, "state", None))
    for agg in getattr(rt, "aggregations", {}).values():
        for store in getattr(agg, "_dstores", {}).values():
            per += tree_shard_bytes(getattr(store, "slab", None))
    return {d: per for d in range(n)}


def shard_events(rt) -> Dict[int, int]:
    """{shard index: events routed} summed over the app's sharded
    queries, from the statistics registry (host counters)."""
    n = shard_count(rt)
    out = {d: 0 for d in range(n)} if n >= 2 else {}
    snap = rt.stats.exposition_snapshot() if rt.stats.enabled else {}
    for _q, per_shard in snap.get("shard_events", {}).items():
        for d, c in enumerate(per_shard):
            if d in out:
                out[d] += int(c)
    return out


def shard_report(rt) -> Optional[Dict[str, Any]]:
    """/healthz `shards` section for one app: per-shard residency +
    routed-event balance with a skew verdict (max/mean of routed events;
    a shard at 0 while others flow reads `idle` — the PART002 lint
    hazard observed live)."""
    n = shard_count(rt)
    if n < 2:
        return None
    ev = shard_events(rt)
    by = shard_state_bytes(rt)
    total = sum(ev.values())
    mean = total / n if n else 0.0
    shards = {}
    for d in range(n):
        e = ev.get(d, 0)
        if total and e == 0:
            status = "idle"
        elif mean and e > 2.0 * mean:
            status = "hot"
        else:
            status = "ok"
        shards[str(d)] = {"events_total": e,
                          "state_bytes": by.get(d, 0),
                          "status": status}
    skew = (max(ev.values()) / mean) if total and mean else None
    report: Dict[str, Any] = {
        "devices": n,
        "layout": "round_robin(slot % n_shards)",
        "balanced": all(s["status"] == "ok" for s in shards.values()),
        "event_skew_max_over_mean":
            round(skew, 3) if skew is not None else None,
        "per_shard": shards,
    }
    # serving emission rings (serving/ring.py): ring slots carry the
    # producing step's sharding with a replicated slot axis, so each
    # device hosts its own segment of every buffered output — report the
    # per-shard resident bytes next to occupancy so operators can see
    # drain lag per device
    rings = {}
    for q, ring in (rt.serve_rings().items()
                    if hasattr(rt, "serve_rings") else ()):
        try:
            rings[q] = {
                "occupancy": ring.occupancy(),
                "capacity": ring.capacity,
                "shard_bytes": sum(tree_shard_bytes(s)
                                   for s in ring.state_leaves()),
            }
        except Exception:  # noqa: BLE001 — metrics must not throw
            continue
    if rings:
        report["serve_rings"] = rings
    return report


def hlo_collectives(compiled) -> List[str]:
    """Sorted collective-op kinds present in a compiled step's HLO text.
    THE one token scan — step_collectives (EXPLAIN) and step_cost's
    collectives mode (the plan auditor) both report it, so a new
    collective appearing in a plan is the same string everywhere."""
    try:
        hlo = compiled.as_text()
    except Exception:  # noqa: BLE001 — diagnostics must not throw
        return []
    return sorted({tok for tok in COLLECTIVE_TOKENS if tok in hlo})


def step_collectives(fn, specs=None) -> Optional[List[str]]:
    """Collective ops in a jitted step's compiled HLO at its last-traced
    signature — or, when it never traced, at synthesized `specs`
    (analysis/signatures.py).  None = no signature available / backend
    refused.  Compiles — EXPLAIN deep mode only, memoized upstream."""
    holder = getattr(fn, "_siddhi_argspec", None)
    traced = holder.get("argspecs") if holder else None
    if traced is not None:
        specs = traced
    if specs is None:
        return None
    try:
        from ..observability.recompile import RECOMPILES
        with RECOMPILES.suppress():
            return hlo_collectives(fn.lower(*specs).compile())
    except Exception:  # noqa: BLE001 — diagnostics must not throw
        return None


def explain_node(qr, kind: str, deep: bool = False) -> Optional[Dict]:
    """EXPLAIN `sharding` section for one query runtime: the shard
    layout its state lives in, per-shard residency, and (deep) the
    collectives its compiled step carries."""
    from .snapshot import query_layout
    p = qr.planned
    mesh = getattr(p, "mesh", None) or getattr(p, "keyed_mesh", None)
    n = shard_count(mesh) if mesh is not None else 1
    if n < 2:
        # GSPMD-placed joins have no key router but ARE sharded
        if kind != "join" or shard_count(getattr(qr.app, "mesh", None)) < 2:
            return None
        n = shard_count(qr.app.mesh)
    node: Dict[str, Any] = {
        "devices": n,
        "per_shard_state_bytes": tree_shard_bytes(qr.state),
    }
    router = router_for(qr)
    if router is not None:
        node["layout"] = "round_robin(slot % n_shards)"
        node["key_capacity"] = router.capacity
        node["keys_per_shard"] = router.block
    layout = query_layout(qr)
    if layout is not None:
        node["snapshot_layout"] = layout
    if deep:
        colls: Dict[str, List[str]] = {}
        from ..observability.explain import _steps_of
        for role, fn in _steps_of(qr, kind):
            c = step_collectives(fn)
            if c:
                colls[role] = c
        node["collectives"] = colls
    return node
