"""Chaos-injection transports: deterministic failure schedules for the
resilience layer.

Reference (what): checkpoint-based engines validate their recovery
paths with injected faults (Flink's chaos/failure-rate restart tests;
the reference's own TestFailingInMemorySink/Source pair used across
OnErrorTestCase).  A robustness claim that was never exercised is a
wish, not a feature.

TPU design (how): `ChaosSink`/`ChaosSource` are REGISTERED transport
types (`type='chaos'`), so any SiddhiQL app can script an outage:

    @sink(type='chaos', id='s1', fail.publishes='3-5',
          on.error='retry', retry.initial.ms='5')
    define stream Out (k string, v int);

Failure schedules are deterministic — `fail.publishes='3-5'` fails
exactly publish attempts 3,4,5 (1-based, counted across retries) —
and the optional `fail.rate` RNG is seeded, so a chaos run replays
bit-identically in CI.  Instances register under their `id` option in
`ChaosSink.instances` / `ChaosSource.instances` for test assertions.

`FakeClock` drives the resilience state machine without real sleeps:
inject it as `SinkConnection._clock`/`_sleep` (tests) so backoff and
breaker probes advance on a virtual timeline.
"""
from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional, Set, Tuple

from ..exceptions import ConnectionUnavailableError
from ..io.broker import InMemoryBroker
from ..io.sink import Sink, register_sink_type
from ..io.source import Source, register_source_type


class FakeClock:
    """Virtual monotonic clock: `sleep` advances time instead of
    waiting.  Wire into a SinkConnection as `conn._clock = clock;
    conn._sleep = clock.sleep` to make backoff/breaker tests instant
    and deterministic."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)
        self.sleeps: List[float] = []

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s

    def sleep(self, s: float) -> bool:
        """SinkConnection._sleep signature: returns False (not
        shutting down) after advancing the virtual clock."""
        self.sleeps.append(s)
        self.t += s
        return False


def parse_schedule(spec: Optional[str]) -> Tuple[Set[int], Optional[int]]:
    """'3-5,9' -> ({3,4,5,9}, None); '4-' -> ({}, 4) meaning "from the
    4th on".  1-based attempt indexes."""
    fixed: Set[int] = set()
    from_n: Optional[int] = None
    if not spec:
        return fixed, from_n
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if part.endswith("-"):
            n = int(part[:-1])
            from_n = n if from_n is None else min(from_n, n)
        elif "-" in part:
            a, b = part.split("-", 1)
            fixed.update(range(int(a), int(b) + 1))
        else:
            fixed.add(int(part))
    return fixed, from_n


class _Schedule:
    def __init__(self, spec: Optional[str], rate: float = 0.0,
                 seed: int = 0):
        self.fixed, self.from_n = parse_schedule(spec)
        self.rate = float(rate)
        self.rng = random.Random(seed)
        self.n = 0

    def fails_next(self) -> bool:
        self.n += 1
        if self.n in self.fixed:
            return True
        if self.from_n is not None and self.n >= self.from_n:
            return True
        return self.rate > 0 and self.rng.random() < self.rate


class ChaosSink(Sink):
    """Delivers to `ChaosSink.instances[id].delivered` (and optionally
    an inMemory broker `topic`) unless the schedule says this publish
    fails.  Options: id, fail.publishes, fail.connects, fail.rate,
    seed, topic."""

    instances: Dict[str, "ChaosSink"] = {}
    _lock = threading.Lock()

    def init(self, options):
        super().init(options)
        self.delivered: List[Any] = []
        self.connects = 0
        self.publish_attempts = 0
        self.failures = 0
        self._pub_sched = _Schedule(options.get("fail.publishes"),
                                    float(options.get("fail.rate", 0.0)),
                                    int(options.get("seed", 0)))
        self._conn_sched = _Schedule(options.get("fail.connects"))
        cid = options.get("id")
        if cid is not None:
            with self._lock:
                ChaosSink.instances[str(cid)] = self

    def connect(self):
        self.connects += 1
        if self._conn_sched.fails_next():
            raise ConnectionUnavailableError(
                f"chaos sink: connect #{self.connects} scheduled to fail")

    def publish(self, payload):
        self.publish_attempts += 1
        if self._pub_sched.fails_next():
            self.failures += 1
            raise ConnectionUnavailableError(
                f"chaos sink: publish #{self.publish_attempts} "
                "scheduled to fail")
        self.delivered.append(payload)
        topic = self.options.get("topic")
        if topic is not None:
            InMemoryBroker.publish(topic, payload)


class ChaosSource(Source):
    """Fails its first `fail.connects` schedule entries, then connects;
    payloads are pushed from tests via `instances[id].emit(payload)`.
    pause()/resume() calls are recorded so tests can assert the
    reconnect loop held the transport down."""

    instances: Dict[str, "ChaosSource"] = {}
    _lock = threading.Lock()

    def init(self, options, deliver):
        super().init(options, deliver)
        self.connects = 0
        self.connected = False
        self.paused = 0
        self.resumed = 0
        self._conn_sched = _Schedule(options.get("fail.connects"))
        cid = options.get("id")
        if cid is not None:
            with self._lock:
                ChaosSource.instances[str(cid)] = self

    def connect(self):
        self.connects += 1
        if self._conn_sched.fails_next():
            raise ConnectionUnavailableError(
                f"chaos source: connect #{self.connects} scheduled to "
                "fail")
        self.connected = True

    def disconnect(self):
        self.connected = False

    def pause(self):
        self.paused += 1

    def resume(self):
        self.resumed += 1

    def emit(self, payload):
        if not self.connected:
            raise ConnectionUnavailableError("chaos source not connected")
        self.deliver(payload)


register_sink_type("chaos", ChaosSink)
register_source_type("chaos", ChaosSource)
