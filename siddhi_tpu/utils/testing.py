"""Test/debug utilities.

Reference (what): CORE/util/EventPrinter.java (print(timestamp, inEvents,
outEvents) used by every sample/test callback) and
CORE/util/SiddhiTestHelper.java:32 (waitForEvents polling helper used across
the reference test suite).

TPU design (how): the printer accepts both the per-event callback shape
(timestamp, in_events, out_events) and the columnar batch-callback payload —
batches print without forcing payload materialization beyond the requested
columns; the wait helper polls a counter the way reference tests do, plus a
flush-aware variant that drains the runtime's async paths first.
"""
from __future__ import annotations

import sys
import time
from typing import Callable


def print_event(timestamp, in_events, out_events=None, out=None) -> None:
    """Drop-in QueryCallback printer (reference: EventPrinter.print)."""
    out = out or sys.stdout
    def fmt(evs):
        if evs is None:
            return "null"
        return "[" + ", ".join(
            "Event{timestamp=%s, data=%s}" % (e.timestamp, list(e.data))
            for e in evs) + "]"
    print(f"Events @ {timestamp}: in:{fmt(in_events)} "
          f"out:{fmt(out_events)}", file=out)


def print_batch(timestamp, payload, out=None) -> None:
    """Batch-callback printer: shows device-computed counts without pulling
    payload columns to host (pass materialize=True for full rows)."""
    out = out or sys.stdout
    counts = {k: payload[k] for k in
              ("n_current", "n_expired", "n_timer", "n_reset")
              if k in payload}
    print(f"Batch @ {timestamp}: {counts}", file=out)


class EventPrinter:
    """Stateful printer that also counts, for quick assertions:

        p = EventPrinter()
        rt.add_callback('q', p)
        ...
        assert p.count == 3
    """

    def __init__(self, out=None, quiet: bool = False):
        self.count = 0
        self.events = []
        self._out = out
        self._quiet = quiet

    def __call__(self, timestamp, in_events, out_events=None):
        evs = list(in_events or [])
        self.events.extend(evs)
        self.count += len(evs)
        if not self._quiet:
            print_event(timestamp, in_events, out_events, out=self._out)


def wait_for_events(get_count: Callable[[], int], expected: int,
                    timeout_s: float = 5.0, interval_s: float = 0.02) -> bool:
    """Poll until `get_count() >= expected` (reference:
    SiddhiTestHelper.waitForEvents :39). Returns False on timeout."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if get_count() >= expected:
            return True
        time.sleep(interval_s)
    return get_count() >= expected


def wait_and_assert(runtime, get_count: Callable[[], int], expected: int,
                    timeout_s: float = 5.0) -> None:
    """Flush the runtime's async paths, then wait; raises AssertionError with
    the observed count on failure."""
    runtime.flush()
    if not wait_for_events(get_count, expected, timeout_s):
        raise AssertionError(
            f"expected {expected} events, saw {get_count()} "
            f"after {timeout_s}s")
