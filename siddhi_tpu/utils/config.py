"""Config system: ConfigManager SPI, InMemory + YAML managers, ConfigReader.

Reference (what, not how): CORE/util/config/ConfigManager.java,
InMemoryConfigManager.java, YAMLConfigManager.java:40 and ConfigReader —
system-wide properties (e.g. ``shardId``, ``partitionById`` for distributed
incremental aggregation, AggregationParser :173-197) plus per-extension
``namespace.name.key`` config read by operators at plan time.  The ``${var}``
env substitution half of the reference config story lives in
compiler/__init__.py (SiddhiCompiler.update_variables).
"""
from __future__ import annotations

from typing import Dict, Optional


class ConfigReader:
    """Per-extension config view (reference: CORE/util/config/ConfigReader).

    Keys are looked up as ``<namespace>.<name>.<key>`` in the manager's
    extension config map.
    """

    def __init__(self, namespace: str, name: str,
                 configs: Optional[Dict[str, str]] = None):
        self.namespace = namespace
        self.name = name
        self._configs = configs or {}

    def read_config(self, key: str, default: Optional[str] = None):
        return self._configs.get(
            f"{self.namespace}.{self.name}.{key}", default)

    def get_all_configs(self) -> Dict[str, str]:
        prefix = f"{self.namespace}.{self.name}."
        return {k[len(prefix):]: v for k, v in self._configs.items()
                if k.startswith(prefix)}

    readConfig = read_config
    getAllConfigs = get_all_configs


class ConfigManager:
    """reference: CORE/util/config/ConfigManager interface."""

    def generate_config_reader(self, namespace: str, name: str) -> ConfigReader:
        return ConfigReader(namespace, name, {})

    def extract_system_configs(self) -> Dict[str, str]:
        return {}

    def extract_property(self, name: str) -> Optional[str]:
        return None

    generateConfigReader = generate_config_reader
    extractSystemConfigs = extract_system_configs
    extractProperty = extract_property


class InMemoryConfigManager(ConfigManager):
    """reference: CORE/util/config/InMemoryConfigManager."""

    def __init__(self, configs: Optional[Dict[str, str]] = None,
                 system_configs: Optional[Dict[str, str]] = None):
        self._configs = dict(configs or {})
        self._system_configs = dict(system_configs or {})

    def generate_config_reader(self, namespace, name):
        return ConfigReader(namespace, name, self._configs)

    def extract_system_configs(self):
        return dict(self._system_configs)

    def extract_property(self, name):
        if name in self._system_configs:
            return self._system_configs[name]
        return self._configs.get(name)


class YAMLConfigManager(ConfigManager):
    """reference: CORE/util/config/YAMLConfigManager.java:40.

    Accepts YAML text (or use :meth:`from_file`) shaped like the reference's
    model (util/config/model/*)::

        properties:
          shardId: wrk-1
          partitionById: "true"
        refs:                       # per-extension configs
          - ref:
              namespace: source
              name: http
              properties:
                port: "8080"
        extensions:                 # flat alternative
          source.http.idle.timeout: "30"
    """

    def __init__(self, yaml_text: str = ""):
        import yaml as _yaml
        data = _yaml.safe_load(yaml_text) if yaml_text else None
        data = data or {}
        if not isinstance(data, dict):
            raise ValueError(
                "YAML config must be a mapping with optional keys "
                f"'properties'/'refs'/'extensions', got {type(data).__name__}")
        self._system: Dict[str, str] = {
            str(k): str(v) for k, v in (data.get("properties") or {}).items()}
        flat: Dict[str, str] = {
            str(k): str(v) for k, v in (data.get("extensions") or {}).items()}
        for entry in data.get("refs") or []:
            ref = entry.get("ref") if isinstance(entry, dict) else None
            if not ref:
                continue
            ns, nm = ref.get("namespace"), ref.get("name")
            if not ns or not nm:
                raise ValueError(
                    f"config ref needs both 'namespace' and 'name': {ref}")
            for k, v in (ref.get("properties") or {}).items():
                flat[f"{ns}.{nm}.{k}"] = str(v)
        self._configs = flat

    @classmethod
    def from_file(cls, path: str) -> "YAMLConfigManager":
        with open(path) as f:
            return cls(f.read())

    def generate_config_reader(self, namespace, name):
        return ConfigReader(namespace, name, self._configs)

    def extract_system_configs(self):
        return dict(self._system)

    def extract_property(self, name):
        if name in self._system:
            return self._system[name]
        return self._configs.get(name)
