"""Persistence stores for state snapshots (reference:
CORE/util/persistence/{PersistenceStore,InMemoryPersistenceStore,
FileSystemPersistenceStore}.java — FileSystemPersistenceStore.save :40).

The snapshot payload here is the pickled state pytree produced by
SiddhiAppRuntime.snapshot() — no stop-the-world object walk, just arrays.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional


class PersistenceStore:
    """SPI: save/load full snapshots by (app, revision)."""

    def save(self, app_name: str, revision: str, snapshot: bytes) -> None:
        raise NotImplementedError

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        raise NotImplementedError

    def get_last_revision(self, app_name: str) -> Optional[str]:
        raise NotImplementedError

    def clear_all_revisions(self, app_name: str) -> None:
        raise NotImplementedError


class InMemoryPersistenceStore(PersistenceStore):
    def __init__(self):
        self._revisions: Dict[str, List[str]] = {}
        self._data: Dict[str, bytes] = {}
        self._lock = threading.RLock()

    def save(self, app_name, revision, snapshot):
        with self._lock:
            self._revisions.setdefault(app_name, []).append(revision)
            self._data[app_name + "__" + revision] = snapshot

    def load(self, app_name, revision):
        return self._data.get(app_name + "__" + revision)

    def get_last_revision(self, app_name):
        revs = self._revisions.get(app_name)
        return revs[-1] if revs else None

    def clear_all_revisions(self, app_name):
        with self._lock:
            for r in self._revisions.pop(app_name, []):
                self._data.pop(app_name + "__" + r, None)


class FileSystemPersistenceStore(PersistenceStore):
    """Snapshots as `<folder>/<app>/<revision>.snapshot` files."""

    def __init__(self, folder: str):
        self.folder = folder

    def _dir(self, app_name: str) -> str:
        return os.path.join(self.folder, app_name)

    def save(self, app_name, revision, snapshot):
        d = self._dir(app_name)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, revision + ".snapshot"), "wb") as f:
            f.write(snapshot)

    def load(self, app_name, revision):
        path = os.path.join(self._dir(app_name), revision + ".snapshot")
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def get_last_revision(self, app_name):
        d = self._dir(app_name)
        if not os.path.isdir(d):
            return None
        revs = sorted(f[:-len(".snapshot")] for f in os.listdir(d)
                      if f.endswith(".snapshot"))
        return revs[-1] if revs else None

    def clear_all_revisions(self, app_name):
        d = self._dir(app_name)
        if os.path.isdir(d):
            for f in os.listdir(d):
                if f.endswith(".snapshot"):
                    os.remove(os.path.join(d, f))


def new_revision(app_name: str) -> str:
    return f"{int(time.time() * 1000)}_{app_name}"
