"""Persistence stores for state snapshots (reference:
CORE/util/persistence/{PersistenceStore,InMemoryPersistenceStore,
FileSystemPersistenceStore}.java — FileSystemPersistenceStore.save :40).

The snapshot payload here is the pickled state pytree produced by
SiddhiAppRuntime.snapshot() — no stop-the-world object walk, just arrays.

Crash safety: filesystem stores write atomically (temp file + fsync +
rename — a crash mid-write leaves the previous revision intact, never a
half-written file under the final name) and seal every blob with a
CRC32 trailer.  `load` verifies the trailer and raises
CorruptSnapshotError on a torn/truncated/rotted file;
SiddhiManager.restore_last_revision catches that and falls back to the
previous good revision instead of dying on restore.
"""
from __future__ import annotations

import os
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..exceptions import CorruptSnapshotError

# trailer = 4-byte big-endian CRC32 of the payload + 4-byte magic; the
# magic distinguishes "sealed blob" from pre-seal legacy files (a pickle
# stream never ends with these bytes)
_CRC_MAGIC = b"SC01"


def seal(blob: bytes) -> bytes:
    """Append the CRC32 integrity trailer."""
    return blob + zlib.crc32(blob).to_bytes(4, "big") + _CRC_MAGIC


def unseal(blob: bytes, where: str = "snapshot",
           strict: bool = True) -> bytes:
    """Verify and strip the CRC trailer.  Raises CorruptSnapshotError on
    a checksum mismatch, and — in strict mode (the filesystem stores,
    which ALWAYS seal on save) — on a missing trailer, which means the
    file was truncated mid-write.  strict=False passes unsealed blobs
    through for stores that may hold pre-seal legacy revisions."""
    if len(blob) >= 8 and blob[-4:] == _CRC_MAGIC:
        body, crc = blob[:-8], int.from_bytes(blob[-8:-4], "big")
        if zlib.crc32(body) != crc:
            raise CorruptSnapshotError(
                f"{where}: CRC32 mismatch — torn write or corruption")
        return body
    if strict:
        raise CorruptSnapshotError(
            f"{where}: integrity trailer missing — truncated or "
            "pre-seal snapshot file")
    return blob


def atomic_write(path: str, data: bytes) -> None:
    """Write-then-rename with fsync so a crash at any instant leaves
    either the old file or the complete new one — never a torn blob."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class PersistenceStore:
    """SPI: save/load full snapshots by (app, revision)."""

    def save(self, app_name: str, revision: str, snapshot: bytes) -> None:
        raise NotImplementedError

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        raise NotImplementedError

    def get_last_revision(self, app_name: str) -> Optional[str]:
        raise NotImplementedError

    def get_revisions(self, app_name: str) -> List[str]:
        """All revisions, oldest first.  Default covers stores that only
        track the last one; restore fallback walks this list newest to
        oldest past corrupt revisions."""
        last = self.get_last_revision(app_name)
        return [last] if last is not None else []

    def clear_all_revisions(self, app_name: str) -> None:
        raise NotImplementedError


class InMemoryPersistenceStore(PersistenceStore):
    def __init__(self):
        self._revisions: Dict[str, List[str]] = {}
        self._data: Dict[str, bytes] = {}
        self._lock = threading.RLock()

    def save(self, app_name, revision, snapshot):
        with self._lock:
            self._revisions.setdefault(app_name, []).append(revision)
            self._data[app_name + "__" + revision] = snapshot

    def load(self, app_name, revision):
        return self._data.get(app_name + "__" + revision)

    def get_last_revision(self, app_name):
        revs = self._revisions.get(app_name)
        return revs[-1] if revs else None

    def get_revisions(self, app_name):
        return list(self._revisions.get(app_name, []))

    def clear_all_revisions(self, app_name):
        with self._lock:
            for r in self._revisions.pop(app_name, []):
                self._data.pop(app_name + "__" + r, None)


class FileSystemPersistenceStore(PersistenceStore):
    """Snapshots as `<folder>/<app>/<revision>.snapshot` files."""

    def __init__(self, folder: str):
        self.folder = folder

    def _dir(self, app_name: str) -> str:
        return os.path.join(self.folder, app_name)

    def save(self, app_name, revision, snapshot):
        d = self._dir(app_name)
        os.makedirs(d, exist_ok=True)
        atomic_write(os.path.join(d, revision + ".snapshot"),
                     seal(snapshot))

    def load(self, app_name, revision):
        path = os.path.join(self._dir(app_name), revision + ".snapshot")
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return unseal(f.read(), where=path)

    def get_last_revision(self, app_name):
        revs = self.get_revisions(app_name)
        return revs[-1] if revs else None

    def get_revisions(self, app_name):
        d = self._dir(app_name)
        if not os.path.isdir(d):
            return []
        return sorted(f[:-len(".snapshot")] for f in os.listdir(d)
                      if f.endswith(".snapshot"))

    def clear_all_revisions(self, app_name):
        d = self._dir(app_name)
        if os.path.isdir(d):
            for f in os.listdir(d):
                if f.endswith(".snapshot"):
                    os.remove(os.path.join(d, f))


def new_revision(app_name: str) -> str:
    return f"{int(time.time() * 1000)}_{app_name}"


class IncrementalPersistenceStore:
    """Incremental snapshot storage: a full BASE snapshot followed by
    op-log INCREMENT snapshots (reference: IncrementalPersistenceStore +
    IncrementalFileSystemPersistenceStore)."""

    def save_base(self, app_name: str, revision: str, blob: bytes) -> None:
        raise NotImplementedError

    def save_increment(self, app_name: str, revision: str,
                       blob: bytes) -> None:
        raise NotImplementedError

    def load_chain(self, app_name: str):
        """Returns (base_blob, [increment blobs in order]) or None."""
        raise NotImplementedError

    def clear_all_revisions(self, app_name: str) -> None:
        raise NotImplementedError


class InMemoryIncrementalPersistenceStore(IncrementalPersistenceStore):
    def __init__(self):
        self._base: Dict[str, Tuple[str, bytes]] = {}
        self._incs: Dict[str, List[Tuple[str, bytes]]] = {}

    def save_base(self, app_name, revision, blob):
        self._base[app_name] = (revision, blob)
        self._incs[app_name] = []

    def save_increment(self, app_name, revision, blob):
        self._incs.setdefault(app_name, []).append((revision, blob))

    def load_chain(self, app_name):
        if app_name not in self._base:
            return None
        return (self._base[app_name][1],
                [b for _, b in self._incs.get(app_name, [])])

    def clear_all_revisions(self, app_name):
        self._base.pop(app_name, None)
        self._incs.pop(app_name, None)


class IncrementalFileSystemPersistenceStore(IncrementalPersistenceStore):
    """reference: CORE/util/persistence/
    IncrementalFileSystemPersistenceStore.java — base + increments as files,
    ordered by revision id."""

    def __init__(self, folder: str):
        self.folder = folder
        os.makedirs(folder, exist_ok=True)

    def _dir(self, app_name):
        d = os.path.join(self.folder, app_name)
        os.makedirs(d, exist_ok=True)
        return d

    def save_base(self, app_name, revision, blob):
        d = self._dir(app_name)
        for f in os.listdir(d):          # new base invalidates old chain
            os.remove(os.path.join(d, f))
        atomic_write(os.path.join(d, f"base_{revision}.snapshot"),
                     seal(blob))

    def save_increment(self, app_name, revision, blob):
        atomic_write(os.path.join(self._dir(app_name),
                                  f"inc_{revision}.snapshot"), seal(blob))

    def load_chain(self, app_name):
        """A corrupt BASE raises (there is nothing older to replay onto);
        a corrupt INCREMENT truncates the chain there — the intact
        prefix still restores, losing only the later deltas, which beats
        losing the whole app state."""
        d = self._dir(app_name)
        bases = sorted(f for f in os.listdir(d) if f.startswith("base_"))
        if not bases:
            return None
        base_path = os.path.join(d, bases[-1])
        with open(base_path, "rb") as f:
            base = unseal(f.read(), where=base_path)
        incs = []
        for name in sorted(f for f in os.listdir(d)
                           if f.startswith("inc_")):
            path = os.path.join(d, name)
            with open(path, "rb") as f:
                try:
                    incs.append(unseal(f.read(), where=path))
                except CorruptSnapshotError as exc:
                    import logging
                    logging.getLogger("siddhi_tpu").warning(
                        "increment chain for %s truncated at corrupt "
                        "%s: %s", app_name, name, exc)
                    break
        return base, incs

    def clear_all_revisions(self, app_name):
        d = self._dir(app_name)
        for f in os.listdir(d):
            os.remove(os.path.join(d, f))


class AsyncSnapshotPersistor:
    """Background snapshot writer so persist() does not block the event path
    (reference: CORE/util/snapshot/AsyncSnapshotPersistor.java:29).

    Write failures are RECORDED, not swallowed: `take_errors()` returns and
    clears them (SiddhiManager.persist uses this to force a fresh BASE
    snapshot after a failed increment, so the chain never has holes), and
    `flush()` raises PersistenceError for failures nobody consumed."""

    def __init__(self):
        import queue
        import threading
        self._q = queue.Queue()
        self._errors: List[Tuple[Optional[str], Exception]] = []
        # tags with a failed write since the last take_failed_tags(); kept
        # separate from _errors so flush() raising does not erase the
        # rebase obligation SiddhiManager.persist reads
        self._failed_tags: set = set()
        self._errors_dropped = 0
        self._elock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="siddhi-persist")
        self._thread.start()

    def submit(self, fn, *args, tag: Optional[str] = None) -> None:
        self._q.put((fn, args, tag))

    def take_errors(self) -> List[Tuple[Optional[str], Exception]]:
        """Failures since the last call, as (tag, exception) pairs."""
        with self._elock:
            errs, self._errors = self._errors, []
            return errs

    def take_failed_tags(self) -> set:
        """Tags of failed writes since the last call (chain-repair signal)."""
        with self._elock:
            tags, self._failed_tags = self._failed_tags, set()
            return tags

    def flush(self) -> None:
        self._q.join()
        with self._elock:
            dropped, self._errors_dropped = self._errors_dropped, 0
        errs = self.take_errors()
        if errs:
            from ..exceptions import PersistenceError
            raise PersistenceError(
                f"{len(errs) + dropped} snapshot write(s) failed: " +
                "; ".join(f"{t or '?'}: {e!r}" for t, e in errs[:10]))

    def _run(self):
        while True:
            fn, args, tag = self._q.get()
            try:
                fn(*args)
            except Exception as exc:  # noqa: BLE001 — persistor must survive
                import logging
                logging.getLogger("siddhi_tpu").error(
                    "async snapshot write failed for %s: %r", tag or "?", exc)
                with self._elock:
                    # bounded: a persist loop against a permanently failing
                    # store must not pin unbounded exception objects
                    if len(self._errors) < 100:
                        self._errors.append((tag, exc))
                    else:
                        self._errors_dropped += 1
                    self._failed_tags.add(tag)
            finally:
                self._q.task_done()
