"""Minimal quartz-style cron evaluator for trigger scheduling.

Reference behavior (what): CORE/trigger/CronTrigger.java:46 schedules via the
Quartz library.  Quartz is a JVM dependency; here a small pure-Python
next-fire computation covers the expression subset the test corpus uses:
``sec min hour day-of-month month day-of-week [year]`` with ``*``, ``?``,
``a``, ``a-b``, ``a,b,c``, ``*/n`` and ``a/n`` per field.
"""
from __future__ import annotations

import datetime
from typing import Optional

_FIELD_RANGES = [
    (0, 59),   # second
    (0, 59),   # minute
    (0, 23),   # hour
    (1, 31),   # day of month
    (1, 12),   # month
    (0, 7),    # day of week (0 and 7 = Sunday, quartz: 1=SUN..7=SAT)
]

_DOW_NAMES = {"SUN": 1, "MON": 2, "TUE": 3, "WED": 4, "THU": 5, "FRI": 6,
              "SAT": 7}
_MON_NAMES = {"JAN": 1, "FEB": 2, "MAR": 3, "APR": 4, "MAY": 5, "JUN": 6,
              "JUL": 7, "AUG": 8, "SEP": 9, "OCT": 10, "NOV": 11, "DEC": 12}


def _parse_field(text: str, lo: int, hi: int,
                 names=None) -> Optional[frozenset]:
    """Returns the allowed value set, or None for 'any'."""
    text = text.strip().upper()
    if text in ("*", "?"):
        return None
    vals = set()
    for part in text.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", "?", ""):
            start, end = lo, hi
        elif "-" in part and not part.lstrip("-").isdigit():
            a, b = part.split("-", 1)
            start = names[a] if names and a in names else int(a)
            end = names[b] if names and b in names else int(b)
        else:
            v = names[part] if names and part in names else int(part)
            if step > 1:
                start, end = v, hi
            else:
                vals.add(v)
                continue
        vals.update(range(start, end + 1, step))
    return frozenset(vals)


class CronExpression:
    def __init__(self, expr: str):
        fields = expr.split()
        if len(fields) == 7:
            fields = fields[:6]  # drop year field
        if len(fields) == 5:
            fields = ["0"] + fields  # classic cron without seconds
        if len(fields) != 6:
            raise ValueError(f"bad cron expression {expr!r}")
        self.sec = _parse_field(fields[0], 0, 59)
        self.min = _parse_field(fields[1], 0, 59)
        self.hour = _parse_field(fields[2], 0, 23)
        self.dom = _parse_field(fields[3], 1, 31)
        self.mon = _parse_field(fields[4], 1, 12, _MON_NAMES)
        # quartz day-of-week: 1=SUN..7=SAT
        self.dow = _parse_field(fields[5], 1, 7, _DOW_NAMES)

    def _dow_ok(self, dt: datetime.datetime) -> bool:
        if self.dow is None:
            return True
        quartz_dow = (dt.weekday() + 1) % 7 + 1   # Mon=2 .. Sun=1
        return quartz_dow in self.dow

    def next_fire(self, after_ms: int) -> int:
        """Earliest firing time strictly after `after_ms` (epoch millis)."""
        dt = datetime.datetime.fromtimestamp(after_ms / 1000.0)
        dt = dt.replace(microsecond=0) + datetime.timedelta(seconds=1)
        limit = dt + datetime.timedelta(days=366 * 4)
        while dt < limit:
            if self.mon is not None and dt.month not in self.mon:
                # jump to first second of next month
                y, m = dt.year + (dt.month == 12), dt.month % 12 + 1
                dt = datetime.datetime(y, m, 1)
                continue
            if (self.dom is not None and dt.day not in self.dom) or \
                    not self._dow_ok(dt):
                dt = (dt + datetime.timedelta(days=1)).replace(
                    hour=0, minute=0, second=0)
                continue
            if self.hour is not None and dt.hour not in self.hour:
                dt = (dt + datetime.timedelta(hours=1)).replace(
                    minute=0, second=0)
                continue
            if self.min is not None and dt.minute not in self.min:
                dt = (dt + datetime.timedelta(minutes=1)).replace(second=0)
                continue
            if self.sec is not None and dt.second not in self.sec:
                dt = dt + datetime.timedelta(seconds=1)
                continue
            return int(dt.timestamp() * 1000)
        raise ValueError("cron expression never fires")
