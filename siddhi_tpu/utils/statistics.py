"""Runtime statistics (reference: CORE/util/statistics/* — Dropwizard
metrics in the reference; here a dependency-free registry with the same
metric roles: throughput per stream, latency per query, memory, buffered
events.  Levels OFF/BASIC/DETAIL, runtime-switchable as in
SiddhiAppRuntimeImpl.setStatisticsLevel :859-895)."""
from __future__ import annotations

import threading
import time
from typing import Dict

OFF, BASIC, DETAIL = "OFF", "BASIC", "DETAIL"


class StatisticsManager:
    def __init__(self, level: str = OFF):
        self.level = level
        self._lock = threading.Lock()
        self._stream_in: Dict[str, int] = {}
        self._query_events: Dict[str, int] = {}
        self._query_time_ns: Dict[str, int] = {}
        self._query_max_ns: Dict[str, int] = {}
        self._start = time.time()

    # -- hook points -----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self.level != OFF

    @property
    def detail(self) -> bool:
        return self.level == DETAIL

    def stream_in(self, stream_id: str, n: int) -> None:
        with self._lock:
            self._stream_in[stream_id] = \
                self._stream_in.get(stream_id, 0) + n

    def query_latency(self, name: str, n: int, elapsed_ns: int) -> None:
        with self._lock:
            self._query_events[name] = self._query_events.get(name, 0) + n
            self._query_time_ns[name] = \
                self._query_time_ns.get(name, 0) + elapsed_ns
            if elapsed_ns > self._query_max_ns.get(name, 0):
                self._query_max_ns[name] = elapsed_ns

    # -- reporting -------------------------------------------------------------
    def report(self, app=None) -> Dict:
        with self._lock:
            elapsed = max(time.time() - self._start, 1e-9)
            out = {
                "level": self.level,
                "uptime_s": elapsed,
                "streams": {
                    sid: {"events": n, "throughput_eps": n / elapsed}
                    for sid, n in self._stream_in.items()},
                "queries": {},
            }
            for name, n in self._query_events.items():
                t = self._query_time_ns.get(name, 0)
                out["queries"][name] = {
                    "events": n,
                    "total_ms": t / 1e6,
                    "avg_latency_us": (t / max(n, 1)) / 1e3,
                    "max_latency_ms": self._query_max_ns.get(name, 0) / 1e6,
                }
        if app is not None:
            mem = 0
            try:
                import jax
                import numpy as np
                for qr in app.query_runtimes.values():
                    for leaf in jax.tree.leaves(qr.state):
                        mem += np.asarray(leaf).nbytes \
                            if not hasattr(leaf, "nbytes") else leaf.nbytes
            except Exception:  # noqa: BLE001 — metrics must not throw
                pass
            out["state_bytes"] = mem
            out["buffered_emissions"] = app._drainer._q.qsize() \
                if app._drainer is not None else 0
        return out

    def reset(self) -> None:
        with self._lock:
            self._stream_in.clear()
            self._query_events.clear()
            self._query_time_ns.clear()
            self._query_max_ns.clear()
            self._start = time.time()
