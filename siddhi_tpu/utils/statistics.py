"""Runtime statistics (reference: CORE/util/statistics/* — Dropwizard
metrics in the reference; here a dependency-free registry with the same
metric roles: throughput per stream, latency per query, memory, buffered
events.  Levels OFF/BASIC/DETAIL, runtime-switchable as in
SiddhiAppRuntimeImpl.setStatisticsLevel :859-895).

TPU additions beyond the reference's scalar gauges (see observability/):
per-query/junction/sink log2 latency HISTOGRAMS (p50/p95/p99/max — tail
latency is the TPU story, averages hide recompile stalls), per-query XLA
recompile counts with triggering shapes, and a DETAIL-level per-batch
pipeline tracer.  Every hot-path hook is guarded by one `enabled` check
and allocates nothing at OFF.
"""
from __future__ import annotations

import sys
import threading
import time
from typing import Dict, Optional

from ..observability.histogram import LogHistogram, hist_of
from ..observability.phases import PhaseProfiler
from ..observability.recompile import RECOMPILES
from ..observability.stateobs import StateObservatory
from ..observability.tracing import PipelineTracer

OFF, BASIC, DETAIL = "OFF", "BASIC", "DETAIL"


class StatisticsManager:
    def __init__(self, level: str = OFF, include: str = ""):
        self.level = level
        # @app:statistics(include='streams.*, queries.q1') — comma-
        # separated fnmatch patterns over report paths (reference:
        # SiddhiStatisticsManager's include filter)
        self.include = [p.strip() for p in include.split(",") if p.strip()]
        self._lock = threading.Lock()
        self._stream_in: Dict[str, int] = {}
        # wall-clock ms of the last batch seen per stream — the /healthz
        # last-event-age probe reads this instead of touching junctions
        self._stream_last_ms: Dict[str, int] = {}
        self._query_events: Dict[str, int] = {}
        self._query_hist: Dict[str, LogHistogram] = {}
        self._junction_hist: Dict[str, LogHistogram] = {}
        self._sink_hist: Dict[str, LogHistogram] = {}
        self._fused_k_hist: Dict[str, LogHistogram] = {}
        # sharded dispatch routing: per-query cumulative events per mesh
        # shard + per-shard batch-occupancy histograms keyed
        # "<query>:shard<d>" (recorded unit: EVENTS, not ns)
        self._shard_events: Dict[str, list] = {}
        self._shard_hist: Dict[str, LogHistogram] = {}
        self._counters: Dict[str, int] = {}
        self.tracer = PipelineTracer()
        # always-on phase accumulator (observability/phases.py): host-
        # clock ns per (query, phase), fed regardless of level — the
        # per-phase budget must survive a BASIC production config
        self.phases = PhaseProfiler()
        # always-on state observatory (observability/stateobs.py):
        # occupancy/high-water per sized device structure + key hotness,
        # fed from host mirrors only — like phases, survives BASIC
        self.stateobs = StateObservatory()
        self._start = time.time()

    def _included(self, path: str) -> bool:
        if not self.include:
            return True
        from fnmatch import fnmatch
        return any(fnmatch(path, p) for p in self.include)

    # -- hook points -----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self.level != OFF

    @property
    def detail(self) -> bool:
        return self.level == DETAIL

    def stream_in(self, stream_id: str, n: int) -> None:
        with self._lock:
            self._stream_in[stream_id] = \
                self._stream_in.get(stream_id, 0) + n
            self._stream_last_ms[stream_id] = int(time.time() * 1000)

    def query_latency(self, name: str, n: int, elapsed_ns: int) -> None:
        hist_of(self._query_hist, name, self._lock).record(elapsed_ns)
        with self._lock:
            self._query_events[name] = self._query_events.get(name, 0) + n

    def e2e_latency(self, name: str, elapsed_ns: int) -> None:
        """Ingest->emission wall-time of one batch, recorded under
        `<query>:e2e`: the clock starts when the send is ACCEPTED (before
        any @async ingress queue) and stops after delivery (callbacks,
        downstream routing, sink publish), so queue wait, @fuse stack
        residency, and @pipeline/@async deferred fetches are all inside —
        per batch, e2e >= the per-hop step latency by construction."""
        hist_of(self._query_hist, name + ":e2e", self._lock) \
            .record(elapsed_ns)

    def e2e_sum_ns(self, name: str) -> int:
        """Total `<query>:e2e` wall ns — the denominator phase_report()
        decomposes (phases + `other` must track this sum)."""
        with self._lock:
            h = self._query_hist.get(name + ":e2e")
        return int(h.sum_ns) if h is not None else 0

    def emitted(self, name: str, rows: int, nbytes: int) -> None:
        """Output rows (and their schema-derived payload bytes) a query
        delivered — the per-tenant `events_out`/`emitted_bytes`
        accounting substrate (observability/timeseries.py)."""
        with self._lock:
            self._counters[f"{name}.emitted_rows"] = \
                self._counters.get(f"{name}.emitted_rows", 0) + rows
            self._counters[f"{name}.emitted_bytes"] = \
                self._counters.get(f"{name}.emitted_bytes", 0) + nbytes

    def junction_latency(self, stream_id: str, elapsed_ns: int) -> None:
        hist_of(self._junction_hist, stream_id, self._lock) \
            .record(elapsed_ns)

    def sink_latency(self, sink_id: str, elapsed_ns: int) -> None:
        hist_of(self._sink_hist, sink_id, self._lock).record(elapsed_ns)

    def counter_inc(self, name: str, n: int = 1) -> None:
        """Generic operational counter (emission drops, cap growths)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def fused_dispatch(self, name: str, k: int, n: int,
                       elapsed_ns: int) -> None:
        """One @fuse dispatch covering k micro-batches (n events):
        latency lands in the query histogram under `<name>:fused` so a
        fused dispatch is not misread as one slow batch, and the
        batches-per-dispatch distribution gets its own log2 histogram
        (quantiles in BATCHES, not ns) — partial flushes and signature
        breaks show up as a left-shifted k distribution."""
        hist_of(self._query_hist, name + ":fused", self._lock) \
            .record(elapsed_ns)
        hist_of(self._fused_k_hist, name, self._lock).record(k)
        with self._lock:
            self._query_events[name + ":fused"] = \
                self._query_events.get(name + ":fused", 0) + n
            self._counters[f"{name}.fused_dispatches"] = \
                self._counters.get(f"{name}.fused_dispatches", 0) + 1
            self._counters[f"{name}.fused_batches"] = \
                self._counters.get(f"{name}.fused_batches", 0) + k

    def shard_events(self, name: str, counts) -> None:
        """Events one sharded dispatch routed to each mesh shard
        (sharding/router.group counts): cumulative per-shard counters
        (`siddhi_shard_events_total` in /metrics, balance verdicts in
        /healthz) plus a per-shard occupancy histogram so routing skew
        shows as diverging p50s, not just diverging totals."""
        with self._lock:
            cur = self._shard_events.get(name)
            if cur is None or len(cur) < len(counts):
                cur = self._shard_events[name] = \
                    [0] * len(counts) if cur is None else \
                    cur + [0] * (len(counts) - len(cur))
        for d, c in enumerate(counts):
            cur[d] += int(c)
            hist_of(self._shard_hist, f"{name}:shard{d}",
                    self._lock).record(int(c))

    # -- recompile projection --------------------------------------------------
    @staticmethod
    def _owners_of(app) -> Optional[list]:
        if app is None:
            return None
        owners = list(getattr(app, "query_runtimes", ()))
        # fused scan steps carry their own recompile label so a K-change
        # recompile is attributed instead of reading as a silent re-trace
        # of the base step
        owners += [f"fused:{q}" for q, qr in
                   getattr(app, "query_runtimes", {}).items()
                   if getattr(qr, "_fuse", None) is not None]
        # merged-group dispatchers (optimizer/mqo.py) compile their own
        # program: `merged:<group>` (+ `fused:merged:<group>` when the
        # group rides a @fuse stack) so recompile blame and the compile
        # gate attribute a merged trace to the group, not to nobody
        for gid, mg in getattr(app, "merged_groups", {}).items():
            owners.append(f"merged:{gid}")
            if getattr(mg, "_fuse", None) is not None:
                owners.append(f"fused:merged:{gid}")
        owners += [f"table:{t}" for t in getattr(app, "tables", ())]
        owners += [f"window:{w}" for w in getattr(app, "named_windows", ())]
        owners += [f"agg:{a}" for a in getattr(app, "aggregations", ())]
        return owners

    def recompiles(self, app=None) -> Dict:
        """Per-owner XLA compile counts + triggering shape signatures,
        projected to the app's queries/tables/windows/aggregations (the
        registry is process-global — see observability/recompile.py)."""
        return RECOMPILES.snapshot(self._owners_of(app))

    # -- exposition ------------------------------------------------------------
    def exposition_snapshot(self) -> Dict:
        """Shallow-copied registries for the Prometheus renderer — the
        histograms are shared read-only references (no bucket copying on
        scrape)."""
        with self._lock:
            return {
                "uptime_s": max(time.time() - self._start, 1e-9),
                "stream_in": dict(self._stream_in),
                "stream_last_ms": dict(self._stream_last_ms),
                "query_events": dict(self._query_events),
                "query_hist": dict(self._query_hist),
                "junction_hist": dict(self._junction_hist),
                "sink_hist": dict(self._sink_hist),
                "fused_k_hist": dict(self._fused_k_hist),
                "shard_events": {k: list(v)
                                 for k, v in self._shard_events.items()},
                "shard_hist": dict(self._shard_hist),
                "counters": dict(self._counters),
                "phases": self.phases.snapshot(),
                "stateobs": self.stateobs.snapshot(),
            }

    # -- reporting -------------------------------------------------------------
    def report(self, app=None) -> Dict:
        with self._lock:
            elapsed = max(time.time() - self._start, 1e-9)
            out = {
                "level": self.level,
                "uptime_s": elapsed,
                "streams": {
                    sid: {"events": n, "throughput_eps": n / elapsed}
                    for sid, n in self._stream_in.items()
                    if self._included(f"streams.{sid}")},
                "queries": {},
            }
            def _quantiles(q, h):
                # total/avg keys kept from the scalar era; the
                # quantiles are the ones that matter on TPU
                q["total_ms"] = h.sum_ns / 1e6
                q["avg_latency_us"] = h.mean_ns / 1e3
                q["p50_us"] = h.quantile(0.50) / 1e3
                q["p95_us"] = h.quantile(0.95) / 1e3
                q["p99_us"] = h.quantile(0.99) / 1e3
                q["max_latency_ms"] = h.max_ns / 1e6
                return q

            for name, n in self._query_events.items():
                if not self._included(f"queries.{name}"):
                    continue
                h = self._query_hist.get(name)
                q = {"events": n}
                if h is not None:
                    _quantiles(q, h)
                out["queries"][name] = q
            for name, h in self._query_hist.items():
                # histogram-only entries (`<q>:e2e` has no event counter
                # of its own): report the sample count as `events`
                if name in out["queries"] or \
                        not self._included(f"queries.{name}"):
                    continue
                out["queries"][name] = _quantiles({"events": h.total}, h)
            if self._junction_hist:
                out["junctions"] = {
                    sid: h.snapshot()
                    for sid, h in self._junction_hist.items()
                    if self._included(f"streams.{sid}")}
            if self._sink_hist:
                out["sinks"] = {sid: h.snapshot()
                                for sid, h in self._sink_hist.items()}
            if self._fused_k_hist:
                # batches-per-dispatch distribution: snapshot() reports in
                # "ns" keys but the recorded unit here is BATCHES
                out["fused_batches_per_dispatch"] = {
                    name: h.snapshot()
                    for name, h in self._fused_k_hist.items()}
            if self._shard_events:
                # per-shard routing totals of sharded queries (the same
                # counters /metrics exports as siddhi_shard_events_total)
                out["shard_events"] = {
                    name: list(v)
                    for name, v in self._shard_events.items()}
            if self._counters:
                out["counters"] = dict(self._counters)
        rec = self.recompiles(app)
        if rec:
            out["recompiles"] = rec
        if app is not None:
            # memory metric (reference: SiddhiMemoryUsageMetric's object-
            # graph walk — here an exact pytree byte count, per query)
            mem_by_query: Dict[str, int] = {}
            try:
                import jax
                import numpy as np
                for name, qr in app.query_runtimes.items():
                    q = 0
                    for leaf in jax.tree.leaves(qr.state):
                        q += np.asarray(leaf).nbytes \
                            if not hasattr(leaf, "nbytes") else leaf.nbytes
                    mem_by_query[name] = q
            except Exception:  # noqa: BLE001 — metrics must not throw
                pass
            out["state_bytes"] = sum(mem_by_query.values())
            out["state_bytes_by_query"] = mem_by_query
            # buffered-events metric (reference: SiddhiBufferedEventsMetric)
            # via the runtime's PUBLIC accessors — a stopped/mid-teardown
            # app reports zeros instead of raising
            try:
                out["buffered_emissions"] = app.buffered_emissions()
                out["buffered_ingress"] = app.buffered_ingress()
            except Exception:  # noqa: BLE001 — metrics must not throw
                out.setdefault("buffered_emissions", 0)
                out.setdefault("buffered_ingress", {})
        return out

    def reset(self) -> None:
        with self._lock:
            self._stream_in.clear()
            self._stream_last_ms.clear()
            self._query_events.clear()
            self._query_hist.clear()
            self._junction_hist.clear()
            self._sink_hist.clear()
            self._fused_k_hist.clear()
            self._shard_events.clear()
            self._shard_hist.clear()
            self._counters.clear()
            self._start = time.time()
        self.phases.reset()
        self.stateobs.reset()


class ConsoleReporter:
    """Periodic metric reporter (reference: SiddhiStatisticsManager
    startReporting :55 — console reporter role).  `@app:statistics(
    reporter='console', interval='5 sec')` or start one programmatically."""

    _WARN_INTERVAL_S = 30.0

    def __init__(self, app, interval_s: float = 5.0, out=None):
        self.app = app
        self.interval_s = interval_s
        self.out = out              # callable(line) or None -> print
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_warn = 0.0

    def start(self) -> "ConsoleReporter":
        if self._thread is not None and self._thread.is_alive():
            return self                   # already running: idempotent
        self._stop.clear()                # restartable after stop()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="siddhi-stats-report")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Idempotent; safe before start() and on repeat calls."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    @staticmethod
    def _quantile_lines(rep: Dict) -> list:
        """Compact per-query tail-latency lines for the periodic report:
        p50/p95/p99/max from the log2 histograms (averages hide recompile
        stalls — the TPU failure mode), with the drop and cap-growth
        counters that flag capped emissions right where the operator is
        already looking."""
        ctr = rep.get("counters", {})
        lines = []
        for name, q in sorted(rep.get("queries", {}).items()):
            if "p50_us" not in q:
                continue
            lines.append(
                f"query {name}: n={q['events']} "
                f"p50={q['p50_us']:.0f}us p95={q['p95_us']:.0f}us "
                f"p99={q['p99_us']:.0f}us "
                f"max={q['max_latency_ms']:.1f}ms "
                f"drops={ctr.get(name + '.dropped', 0)} "
                f"cap_growths={ctr.get(name + '.cap_growths', 0)}")
        return lines

    def _run(self) -> None:
        import json
        while not self._stop.wait(self.interval_s):
            try:
                rep = self.app.statistics()
                out = self.out if self.out is not None else \
                    (lambda s: print(f"[siddhi-stats] {s}", flush=True))
                # first line stays machine-readable JSON (scrapers parse
                # it); the quantile summary lines follow for humans
                out(json.dumps(rep, default=str))
                for line in self._quantile_lines(rep):
                    out(line)
            except Exception as exc:  # noqa: BLE001 — reporter must not die
                # rate-limited warning instead of a silent swallow: a
                # reporter that dies quietly looks like a healthy app with
                # frozen metrics
                now = time.monotonic()
                if now - self._last_warn >= self._WARN_INTERVAL_S:
                    self._last_warn = now
                    print(f"[siddhi-stats] report failed: {exc!r}",
                          file=sys.stderr, flush=True)
