"""Runtime statistics (reference: CORE/util/statistics/* — Dropwizard
metrics in the reference; here a dependency-free registry with the same
metric roles: throughput per stream, latency per query, memory, buffered
events.  Levels OFF/BASIC/DETAIL, runtime-switchable as in
SiddhiAppRuntimeImpl.setStatisticsLevel :859-895)."""
from __future__ import annotations

import threading
import time
from typing import Dict

OFF, BASIC, DETAIL = "OFF", "BASIC", "DETAIL"


class StatisticsManager:
    def __init__(self, level: str = OFF, include: str = ""):
        self.level = level
        # @app:statistics(include='streams.*, queries.q1') — comma-
        # separated fnmatch patterns over report paths (reference:
        # SiddhiStatisticsManager's include filter)
        self.include = [p.strip() for p in include.split(",") if p.strip()]
        self._lock = threading.Lock()
        self._stream_in: Dict[str, int] = {}
        self._query_events: Dict[str, int] = {}
        self._query_time_ns: Dict[str, int] = {}
        self._query_max_ns: Dict[str, int] = {}
        self._start = time.time()

    def _included(self, path: str) -> bool:
        if not self.include:
            return True
        from fnmatch import fnmatch
        return any(fnmatch(path, p) for p in self.include)

    # -- hook points -----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self.level != OFF

    @property
    def detail(self) -> bool:
        return self.level == DETAIL

    def stream_in(self, stream_id: str, n: int) -> None:
        with self._lock:
            self._stream_in[stream_id] = \
                self._stream_in.get(stream_id, 0) + n

    def query_latency(self, name: str, n: int, elapsed_ns: int) -> None:
        with self._lock:
            self._query_events[name] = self._query_events.get(name, 0) + n
            self._query_time_ns[name] = \
                self._query_time_ns.get(name, 0) + elapsed_ns
            if elapsed_ns > self._query_max_ns.get(name, 0):
                self._query_max_ns[name] = elapsed_ns

    # -- reporting -------------------------------------------------------------
    def report(self, app=None) -> Dict:
        with self._lock:
            elapsed = max(time.time() - self._start, 1e-9)
            out = {
                "level": self.level,
                "uptime_s": elapsed,
                "streams": {
                    sid: {"events": n, "throughput_eps": n / elapsed}
                    for sid, n in self._stream_in.items()
                    if self._included(f"streams.{sid}")},
                "queries": {},
            }
            for name, n in self._query_events.items():
                if not self._included(f"queries.{name}"):
                    continue
                t = self._query_time_ns.get(name, 0)
                out["queries"][name] = {
                    "events": n,
                    "total_ms": t / 1e6,
                    "avg_latency_us": (t / max(n, 1)) / 1e3,
                    "max_latency_ms": self._query_max_ns.get(name, 0) / 1e6,
                }
        if app is not None:
            # memory metric (reference: SiddhiMemoryUsageMetric's object-
            # graph walk — here an exact pytree byte count, per query)
            mem_by_query: Dict[str, int] = {}
            try:
                import jax
                import numpy as np
                for name, qr in app.query_runtimes.items():
                    q = 0
                    for leaf in jax.tree.leaves(qr.state):
                        q += np.asarray(leaf).nbytes \
                            if not hasattr(leaf, "nbytes") else leaf.nbytes
                    mem_by_query[name] = q
            except Exception:  # noqa: BLE001 — metrics must not throw
                pass
            out["state_bytes"] = sum(mem_by_query.values())
            out["state_bytes_by_query"] = mem_by_query
            # buffered-events metric (reference: SiddhiBufferedEventsMetric)
            out["buffered_emissions"] = app._drainer._q.qsize() \
                if app._drainer is not None else 0
            pend = {sid: j.pending_async()
                    for sid, j in app.junctions.items()}
            out["buffered_ingress"] = {
                sid: n for sid, n in pend.items() if n > 0}
        return out

    def reset(self) -> None:
        with self._lock:
            self._stream_in.clear()
            self._query_events.clear()
            self._query_time_ns.clear()
            self._query_max_ns.clear()
            self._start = time.time()


class ConsoleReporter:
    """Periodic metric reporter (reference: SiddhiStatisticsManager
    startReporting :55 — console reporter role).  `@app:statistics(
    reporter='console', interval='5 sec')` or start one programmatically."""

    def __init__(self, app, interval_s: float = 5.0, out=None):
        self.app = app
        self.interval_s = interval_s
        self.out = out              # callable(line) or None -> print
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "ConsoleReporter":
        self._stop.clear()            # restartable after stop()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="siddhi-stats-report")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        import json
        while not self._stop.wait(self.interval_s):
            try:
                line = json.dumps(self.app.statistics(), default=str)
                if self.out is not None:
                    self.out(line)
                else:
                    print(f"[siddhi-stats] {line}", flush=True)
            except Exception:  # noqa: BLE001 — reporter must not die
                pass
