"""siddhi-lint CLI: static TPU-hazard analysis of SiddhiQL app files.

    python -m siddhi_tpu.tools.lint app.siddhi [more.siddhi ...]
        [--format text|json] [--fail-on info|warn|error]
        [--disable RULE[,RULE...]] [--state-budget BYTES]
        [--mesh-size N] [--rules]

Exit-code contract (stable — CI scripts key on it):
    0   no finding at or above the --fail-on severity (default: error)
    1   at least one finding at or above the threshold
    2   usage error, unreadable file, or SiddhiQL parse error

Analysis is purely static (parse + plan-fact derivation): linting a
broken-at-runtime app never constructs a runtime, traces, or compiles.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List

from ..analysis import (
    LintConfig,
    analyze,
    catalog,
    counts,
    report,
    severity_rank,
)
from ..compiler.tokenizer import SiddhiParserException

_FAIL_LEVELS = {"info": "INFO", "warn": "WARN", "error": "ERROR"}


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m siddhi_tpu.tools.lint",
        description="Static plan analyzer: catches TPU hazards "
                    "(unbounded state, ignored @fuse, cap overflow, "
                    "dead dataflow) before an app ever runs.")
    p.add_argument("files", nargs="*", metavar="app.siddhi",
                   help="SiddhiQL app files to analyze")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--fail-on", choices=tuple(_FAIL_LEVELS),
                   default="error",
                   help="exit 1 when any finding is at or above this "
                        "severity (default: error)")
    p.add_argument("--disable", default="",
                   help="comma-separated rule IDs to skip")
    p.add_argument("--state-budget", type=int, default=None,
                   metavar="BYTES",
                   help="MEM001 device-state budget in bytes "
                        "(default: 128 MiB)")
    p.add_argument("--mesh-size", type=int, default=0, metavar="N",
                   help="PART002 deploy target: shard-mesh device count "
                        "the app will serve on (default: unknown — "
                        "PART002 stays silent)")
    p.add_argument("--global-ceiling", type=int, default=0,
                   metavar="BYTES",
                   help="ADM001 deploy target: the box's "
                        "admission.global.max.state.bytes ceiling "
                        "(default: unknown — ADM001's size half stays "
                        "silent)")
    p.add_argument("--rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def _print_rules(fmt: str) -> None:
    cat = catalog()
    if fmt == "json":
        print(json.dumps(cat, indent=2))
        return
    for r in cat:
        print(f"{r['id']}  {r['severity']:5s} {r['title']}")
        print(f"    why: {r['rationale']}")
        print(f"    fix: {r['hint']}")


def main(argv: List[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.rules:
        _print_rules(args.format)
        return 0
    if not args.files:
        print("error: no app files given (see --help)", file=sys.stderr)
        return 2

    config = LintConfig(
        disabled={r.strip() for r in args.disable.split(",")
                  if r.strip()})
    if args.state_budget is not None:
        config.state_budget_bytes = args.state_budget
    if args.mesh_size:
        config.mesh_devices = args.mesh_size
    if args.global_ceiling:
        config.global_state_ceiling_bytes = args.global_ceiling
    threshold = severity_rank(_FAIL_LEVELS[args.fail_on])

    failed = False
    json_out = {}
    for path in args.files:
        try:
            with open(path, "r") as fh:
                source = fh.read()
        except OSError as exc:
            print(f"error: cannot read {path}: {exc}", file=sys.stderr)
            return 2
        try:
            findings = analyze(source, config=config, source_name=path)
        except SiddhiParserException as exc:
            print(f"{path}: PARSE ERROR {exc}", file=sys.stderr)
            return 2
        if any(severity_rank(f.severity) >= threshold
               for f in findings):
            failed = True
        if args.format == "json":
            json_out[path] = report(findings)
        else:
            for f in findings:
                print(f.render())
            c = counts(findings)
            print(f"{path}: {c['ERROR']} error(s), {c['WARN']} "
                  f"warning(s), {c['INFO']} info")
    if args.format == "json":
        print(json.dumps(json_out, indent=2, sort_keys=True))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
