"""Extension documentation generator.

Reference (what): modules/siddhi-doc-gen — Maven mojos rendering mkdocs
markdown from @Extension metadata (DocumentationUtils.java:84).
TPU design (how): walk THIS framework's live registries (window types,
stream functions, aggregators, scalar extensions, record stores) and render
one markdown page per extension category from their docstrings — no build
plugin, just `python -m siddhi_tpu.tools.docgen [outdir]`.
"""
from __future__ import annotations

import inspect
import os
from typing import Dict, List, Tuple


def _first_paragraph(doc: str) -> str:
    doc = inspect.cleandoc(doc or "").strip()
    return doc.split("\n\n")[0].replace("\n", " ") if doc else "(undocumented)"


def collect() -> Dict[str, List[Tuple[str, str]]]:
    """{category: [(name, summary)]} from the live registries."""
    from ..core import window as win
    from ..core.streamfn import STREAM_FUNCTIONS
    from ..core.executor import AGGREGATOR_NAMES
    from ..core.extension import extension_metadata, scalar_function_registry
    from ..io.store import store_registry

    out: Dict[str, List[Tuple[str, str]]] = {}
    meta = extension_metadata()
    out["windows"] = sorted(
        (name, _first_paragraph(cls.__doc__))
        for name, cls in win.WINDOW_TYPES.items())
    out["stream-functions"] = sorted(
        (name, _first_paragraph(
            getattr(fn, "__doc__", "") or type(fn).__doc__))
        for name, fn in STREAM_FUNCTIONS.items())
    from ..core.extension import (attribute_aggregator_registry,
                                  incremental_aggregator_registry,
                                  script_engine_registry)
    from ..io.mappers import SINK_MAPPERS, SOURCE_MAPPERS
    from ..io.sink import DIST_STRATEGIES
    out["aggregators"] = sorted(
        [(n, "") for n in AGGREGATOR_NAMES] +
        [(n, _first_paragraph(cls.__doc__))
         for n, cls in attribute_aggregator_registry().items()])
    out["source-mappers"] = sorted(
        (name, _first_paragraph(cls.__doc__))
        for name, cls in SOURCE_MAPPERS.items())
    out["sink-mappers"] = sorted(
        (name, _first_paragraph(cls.__doc__))
        for name, cls in SINK_MAPPERS.items())
    out["script-engines"] = sorted(
        (name, _first_paragraph(fn.__doc__))
        for name, fn in script_engine_registry().items())
    out["incremental-aggregators"] = sorted(
        (name, _first_paragraph(cls.__doc__))
        for name, cls in incremental_aggregator_registry().items())
    out["distribution-strategies"] = sorted(
        (name, _first_paragraph(cls.__doc__))
        for name, cls in DIST_STRATEGIES.items())
    def _scalar_summary(name, fn):
        m = meta.get(f"scalar_function:{name}")
        return (m.description if m else "") or \
            _first_paragraph(getattr(fn, "__doc__", ""))
    out["scalar-extensions"] = sorted(
        (name, _scalar_summary(name, fn))
        for name, fn in scalar_function_registry().items())
    out["stores"] = sorted(
        (name, _first_paragraph(cls.__doc__))
        for name, cls in store_registry().items())
    # lint rule catalog straight from the analyzer's registry — the doc
    # and the shipped rule set cannot drift (ID, severity, rationale,
    # fix hint all come from the same Rule dataclass)
    from ..analysis import catalog as lint_catalog
    out["lint-rules"] = [
        (r["id"],
         f"**{r['severity']}** — {r['title']}. {r['rationale']} "
         f"*Fix:* {r['hint']}")
        for r in lint_catalog()]
    # plan-audit metric catalog from the auditor's Metric dataclasses
    # (analysis/audit.py METRICS) — same no-drift contract: the gate's
    # tolerances and the doc are one table.  CI regenerates this page
    # (and lint-rules.md) and fails on diff.
    from ..analysis.audit import METRICS
    out["audit-metrics"] = [
        (m.name,
         (f"gate: **{m.gate}**"
          + (f", tolerance ±{m.tolerance * 100:g}%"
             if m.gate == "increase" else "")
          + f" — {m.description}"))
        for m in METRICS]
    return out


def render(collected: Dict[str, List[Tuple[str, str]]]) -> Dict[str, str]:
    """{filename: markdown} mkdocs-style pages."""
    pages: Dict[str, str] = {}
    index = ["# siddhi_tpu extensions", "",
             "Generated from the live extension registries "
             "(reference role: siddhi-doc-gen).", ""]
    for cat, items in collected.items():
        index.append(f"- [{cat}]({cat}.md) ({len(items)})")
        lines = [f"# {cat}", ""]
        for name, summary in items:
            lines.append(f"## {name}")
            lines.append("")
            if summary:
                lines.append(summary)
                lines.append("")
        pages[f"{cat}.md"] = "\n".join(lines) + "\n"
    pages["index.md"] = "\n".join(index) + "\n"
    return pages


def write(outdir: str) -> List[str]:
    os.makedirs(outdir, exist_ok=True)
    pages = render(collect())
    written = []
    for fname, content in pages.items():
        path = os.path.join(outdir, fname)
        with open(path, "w") as f:
            f.write(content)
        written.append(path)
    return written


if __name__ == "__main__":
    import sys
    target = sys.argv[1] if len(sys.argv) > 1 else "docs/extensions"
    for p in write(target):
        print(p)
