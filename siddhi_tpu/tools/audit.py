"""siddhi-audit CLI: compiled-plan cost fingerprints vs the baseline.

    python -m siddhi_tpu.tools.audit check  [options]
    python -m siddhi_tpu.tools.audit update [options]
    python -m siddhi_tpu.tools.audit diff   [options]

    options:
        --baseline PATH     baseline file (default: PLAN_BASELINE.json
                            at the repository root)
        --corpus DIR        sample-app directory (default: samples/apps)
        --no-bench          audit only the sample apps, not the bench
                            serving shapes
        --format text|json  report format (default: text)
        --tolerance M=REL   override one metric's relative tolerance
                            (repeatable), e.g. --tolerance flops=0.10

Subcommands:
    check   fingerprint the corpus, diff against the baseline, and GATE:
            exit 0 clean, 1 on any regression, 2 on error.  This is the
            CI entry (`make audit`): a PR that silently doubles a
            query's bytes-accessed or adds a collective fails here,
            before any benchmark runs.
    update  re-fingerprint and REWRITE the baseline.  Run it when a
            plan change is intentional, commit PLAN_BASELINE.json, and
            say why in the PR.
    diff    print every delta (including within-tolerance improvements)
            without gating — exit 0 unless extraction itself fails.

The audit never executes traffic: it plans the corpus apps, synthesizes
canonical step signatures, and re-lowers under RECOMPILES.suppress()
(analysis/audit.py; guard-tested in tests/test_audit.py).
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from ..analysis import audit as _audit


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m siddhi_tpu.tools.audit",
        description="Compiled-plan cost fingerprint regression gate "
                    "(flops/bytes/memory/collectives from EXPLAIN, "
                    "never executing traffic).")
    p.add_argument("command", choices=("check", "update", "diff"))
    p.add_argument("--baseline", default=None, metavar="PATH")
    p.add_argument("--corpus", default=None, metavar="DIR",
                   help="sample-app directory (default: samples/apps)")
    p.add_argument("--no-bench", action="store_true",
                   help="skip the flagship/windowed_join/block-NFA "
                        "bench shapes")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--tolerance", action="append", default=[],
                   metavar="METRIC=REL",
                   help="override a relative tolerance, e.g. "
                        "flops=0.10 (repeatable)")
    return p


def _tolerances(pairs: List[str]):
    out = {}
    for pair in pairs:
        if "=" not in pair:
            raise ValueError(f"--tolerance wants METRIC=REL, got "
                             f"{pair!r}")
        k, v = pair.split("=", 1)
        if k not in _audit.DEFAULT_TOLERANCES:
            raise ValueError(
                f"unknown metric {k!r} (known: "
                f"{', '.join(sorted(_audit.DEFAULT_TOLERANCES))})")
        out[k] = float(v)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    try:
        tol = _tolerances(args.tolerance)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    try:
        if args.command == "update":
            baseline = _audit.build_baseline(
                samples_dir=args.corpus,
                include_bench=not args.no_bench)
            path = _audit.save_baseline(baseline, args.baseline)
            n_shapes = len(baseline["corpus"])
            n_queries = sum(len(e["queries"])
                            for e in baseline["corpus"].values())
            print(f"wrote {path}: {n_shapes} shapes, "
                  f"{n_queries} query fingerprints")
            for s in baseline.get("skipped_at_update", ()):
                print(f"note: skipped {s} (too few devices here)",
                      file=sys.stderr)
            return 0

        baseline = _audit.load_baseline(args.baseline)
        current, skipped = _audit.corpus_fingerprints(
            samples_dir=args.corpus,
            include_bench=not args.no_bench)
        deltas = _audit.diff_fingerprints(baseline, current,
                                          skipped=skipped,
                                          tolerances=tol)
    except FileNotFoundError as exc:
        print(f"error: {exc} — run `python -m siddhi_tpu.tools.audit "
              "update` to create the baseline", file=sys.stderr)
        return 2
    except Exception as exc:  # noqa: BLE001 — CLI boundary
        print(f"error: {exc!r}", file=sys.stderr)
        return 2

    shown = deltas if args.command == "diff" else \
        [d for d in deltas if d.level != "note"] or deltas
    if args.format == "json":
        print(json.dumps({
            "command": args.command,
            "deltas": [d.to_dict() for d in shown],
            "regressions": sum(d.level == "regression" for d in deltas),
            "improvements": sum(d.level == "improvement"
                                for d in deltas),
        }, indent=2, sort_keys=True))
    else:
        for d in shown:
            print(d.render())
        n_reg = sum(d.level == "regression" for d in deltas)
        n_imp = sum(d.level == "improvement" for d in deltas)
        print(f"audit {args.command}: {n_reg} regression(s), "
              f"{n_imp} improvement(s) across "
              f"{len(baseline.get('corpus', {}))} baselined shapes")
        if n_imp and not n_reg:
            print("improvements only — consider refreshing the "
                  "baseline (`audit update`) so the win is pinned")

    if args.command == "diff":
        return 0
    return 1 if _audit.has_regressions(deltas) else 0


if __name__ == "__main__":
    sys.exit(main())
