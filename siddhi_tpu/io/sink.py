"""Sinks: egress with mappers, log sink, distributed publishing strategies
(reference: CORE/stream/output/sink/Sink.java:59, LogSink.java,
InMemorySink.java:115, distributed/RoundRobin:99 + Partitioned:111).
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

from ..core import event as ev
from ..exceptions import ConnectionUnavailableError
from .broker import InMemoryBroker
from .mappers import SINK_MAPPERS, SinkMapper
from .resilience import SINK_POLICIES, BackoffPolicy, SinkConnection

log = logging.getLogger("siddhi_tpu")


class Sink:
    """Transport SPI: subclass and register with register_sink_type.

    `self.config_reader` (scoped to `sink.<type>.*`) is injected before
    init — reference: Sink.init receives a ConfigReader
    (CORE/stream/output/sink/Sink.java:59 via DefinitionParserHelper)."""

    config_reader = None

    def init(self, options: Dict[str, Any]):
        self.options = options

    def connect(self) -> None:
        pass

    def disconnect(self) -> None:
        pass

    def publish(self, payload: Any) -> None:
        raise NotImplementedError


class InMemorySink(Sink):
    def publish(self, payload):
        try:
            InMemoryBroker.publish(self.options.get("topic"), payload)
        except Exception as exc:  # noqa: BLE001 — typed transport failure
            raise ConnectionUnavailableError(
                f"inMemory broker delivery on topic "
                f"{self.options.get('topic')!r} failed: {exc!r}") from exc


class LogSink(Sink):
    """reference: CORE/stream/output/sink/LogSink.java:194"""

    def publish(self, payload):
        prefix = self.options.get("prefix", "")
        try:
            log.info("%s%s", prefix + (" : " if prefix else ""), payload)
        except Exception as exc:  # noqa: BLE001 — typed transport failure
            raise ConnectionUnavailableError(
                f"log sink emit failed: {exc!r}") from exc


def _stable_hash(v) -> int:
    """Deterministic partition hash: Python's hash() is salted per process
    (PYTHONHASHSEED), which would route the same key to different
    @destination endpoints across sender processes/restarts — fatal for
    cross-host sharded pipelines (reference: PartitionedTransport routes on
    a stable key hash)."""
    import zlib
    return zlib.crc32(repr(v).encode())


SINK_TYPES: Dict[str, type] = {"inMemory": InMemorySink, "log": LogSink}


def register_sink_type(name: str, cls: type) -> None:
    SINK_TYPES[name] = cls


class DistributionStrategy:
    """@distribution strategy SPI (reference: distributed/
    DistributionStrategy.java — RoundRobin:99 / Partitioned:111 in core;
    custom strategies register with @distribution_strategy or
    setExtension).  One instance per distributed sink."""

    def init(self, schema, dist_ann, n_destinations: int) -> None:
        self.schema = schema
        self.ann = dist_ann
        self.n = n_destinations

    def destination(self, event, payload) -> int:
        """Destination index in [0, n) for one event/payload."""
        raise NotImplementedError


class RoundRobinStrategy(DistributionStrategy):
    """Cycles destinations (reference: RoundRobinStrategy.java:99)."""

    def init(self, schema, dist_ann, n_destinations):
        super().init(schema, dist_ann, n_destinations)
        self._rr = 0

    def destination(self, event, payload):
        i = self._rr % self.n
        self._rr += 1
        return i


class PartitionedStrategy(DistributionStrategy):
    """Stable-hash routing on partitionKey (reference:
    PartitionedStrategy.java:111)."""

    def init(self, schema, dist_ann, n_destinations):
        super().init(schema, dist_ann, n_destinations)
        key = dist_ann.element("partitionKey")
        if key is None:
            raise ValueError("partitioned distribution needs partitionKey=")
        self._pos = schema.position(key)

    def destination(self, event, payload):
        if event is None:
            raise ValueError(
                "partitioned distribution needs a 1:1 sink mapper (the "
                "mapper emitted a different payload count, so payloads "
                "cannot be matched to their events' partition keys)")
        return _stable_hash(event.data[self._pos]) % self.n


DIST_STRATEGIES: Dict[str, type] = {
    "roundrobin": RoundRobinStrategy,
    "partitioned": PartitionedStrategy,
}


class SinkRuntime:
    """Wires one @sink annotation: stream events -> mapper -> transport(s).

    `@sink(..., @distribution(strategy='roundRobin'|'partitioned',
    partitionKey='attr', @destination(topic='t1'), @destination(topic='t2')))`
    publishes across destinations (reference: DistributedTransport + its
    RoundRobin/Partitioned strategies).

    `@sink(on.error='log'|'retry'|'wait'|'stream'|'store')` selects the
    failure policy (reference: Sink.OnErrorAction + the error store).
    Every transport is wrapped in a `SinkConnection` state machine
    (io/resilience.py) — retry/wait mechanics and the circuit breaker
    live there; 'stream' routes failed events into the `!stream` fault
    stream, 'store' hands them to `runtime.error_store`.  Tunables ride
    the annotation: retry.initial.ms / retry.multiplier / retry.max.ms /
    retry.jitter / retry.seed / buffer.size / breaker.failures /
    wait.timeout.ms / probe.interval.ms."""

    def __init__(self, stream_id: str, ann, app):
        self.stream_id = stream_id
        self.app = app
        stype = ann.element("type") or ann.element(None)
        if stype is None:
            raise ValueError(f"@sink on {stream_id!r} needs type=")
        if stype not in SINK_TYPES:
            raise ValueError(
                f"unknown sink type {stype!r}; registered: "
                f"{sorted(SINK_TYPES)}")
        self.options = ann.named_elements()
        self.on_error = str(self.options.get("on.error", "log")).lower()
        if self.on_error not in SINK_POLICIES:
            raise ValueError(
                f"@sink on {stream_id!r}: unknown on.error="
                f"{self.on_error!r}; one of {SINK_POLICIES}")
        self.failed_total = 0
        if self.on_error == "stream":
            # the fault stream must exist before traffic flows, exactly
            # as @OnError(action='STREAM') would have defined it
            app._ensure_fault_stream(stream_id)
        map_ann = dist_ann = None
        for sub in ann.annotations:
            n = sub.name.lower()
            if n == "map":
                map_ann = sub
            elif n == "distribution":
                dist_ann = sub
        mtype = (map_ann.element("type") if map_ann else None) or \
            "passThrough"
        if mtype not in SINK_MAPPERS:
            raise ValueError(f"unknown sink map type {mtype!r}")
        schema = app.schemas[stream_id]
        self.mapper: SinkMapper = SINK_MAPPERS[mtype](schema, map_ann)

        self.sinks: List[Sink] = []
        self.strategy: Optional[DistributionStrategy] = None
        if dist_ann is not None:
            sname = str(dist_ann.element("strategy") or "roundRobin")
            scls = DIST_STRATEGIES.get(sname.lower())
            if scls is None:
                raise ValueError(
                    f"unknown distribution strategy {sname!r}; registered: "
                    f"{sorted(DIST_STRATEGIES)}")
            self.strategy = scls()
            for dest in dist_ann.annotations:
                if dest.name.lower() == "destination":
                    opts = dict(self.options)
                    opts.update(dest.named_elements())
                    s = SINK_TYPES[stype]()
                    s.config_reader = \
                        app.config_manager.generate_config_reader(
                            "sink", str(stype))
                    s.init(opts)
                    self.sinks.append(s)
            if not self.sinks:
                raise ValueError("@distribution needs @destination(...)s")
            self.strategy.init(schema, dist_ann, len(self.sinks))
        else:
            s = SINK_TYPES[stype]()
            s.config_reader = app.config_manager.generate_config_reader(
                "sink", str(stype))
            s.init(self.options)
            self.sinks.append(s)
        self.connections: List[SinkConnection] = [
            self._wrap(s) for s in self.sinks]

    def _wrap(self, s: Sink) -> SinkConnection:
        opts = self.options
        import random
        seed = opts.get("retry.seed")
        rng = random.Random(int(seed)) if seed is not None else None
        probe = opts.get("probe.interval.ms")
        return SinkConnection(
            s, stream_id=self.stream_id, policy=self.on_error,
            backoff=BackoffPolicy.from_options(opts, rng=rng),
            buffer_size=int(opts.get("buffer.size", 1024)),
            breaker_failures=int(opts.get("breaker.failures", 5)),
            wait_timeout_s=float(opts.get("wait.timeout.ms", 30000)) / 1e3,
            probe_interval_s=float(probe) / 1e3 if probe is not None
            else None)

    def start(self) -> None:
        for c in self.connections:
            c.connect()

    def stop(self) -> None:
        for c in self.connections:
            c.close()

    # StreamCallback entry
    def __call__(self, events: List[ev.Event]) -> None:
        stats = self.app.stats
        if not stats.enabled:
            self._flush(events)
            return
        from ..observability import tracing as _tracing
        t0 = time.perf_counter_ns()
        try:
            if _tracing.active() is not None:
                with _tracing.span("sink", stream=self.stream_id,
                                   events=len(events)):
                    self._flush(events)
            else:
                self._flush(events)
        finally:
            stats.sink_latency(self.stream_id, time.perf_counter_ns() - t0)

    def _flush(self, events: List[ev.Event]) -> None:
        payloads = self.mapper.map(events)
        if self.strategy is None or len(self.connections) == 1:
            pairs = zip(events, payloads) \
                if len(payloads) == len(events) \
                else ((None, p) for p in payloads)
            pairs = [(e, p, self.connections[0]) for e, p in pairs]
        else:
            if len(payloads) == len(events):
                raw = zip(events, payloads)
            else:
                # a custom mapper emitted N payloads per event: every
                # payload still publishes; event-based strategies
                # (partitioned) get event=None and must reject it rather
                # than drop data
                raw = ((None, p) for p in payloads)
            pairs = [(e, p, self.connections[
                self.strategy.destination(e, p) % len(self.connections)])
                for e, p in raw]
        # per-payload isolation: one failing payload must never silently
        # drop the remainder of the batch (the pre-resilience _flush
        # raised out of the loop and lost every later payload)
        failed = []
        first_app_exc = None
        for e, p, conn in pairs:
            try:
                conn.publish(p)
            except ConnectionUnavailableError as exc:
                failed.append((e, exc, conn))
            except Exception as exc:  # noqa: BLE001 — app-level bug
                log.error("sink for %r: publish raised a non-transport "
                          "error (payload isolated, batch continues): %r",
                          self.stream_id, exc)
                first_app_exc = first_app_exc or exc
        if failed:
            self._handle_failed(failed)
        if first_app_exc is not None:
            # surfaced AFTER the whole batch published, so the junction's
            # fault routing sees it without costing the other payloads
            raise first_app_exc

    def _handle_failed(self, failed) -> None:
        """Route events whose transport publish terminally failed, per
        on.error: 'stream' -> `!stream` fault path, 'store' -> error
        store, else log-and-count.  ('retry' buffers inside the
        connection and only lands here on breaker shed/buffer overflow
        of the direct path; 'wait' lands here after its deadline.)"""
        self.failed_total += len(failed)
        evs = [e for e, _, _ in failed if e is not None]
        exc = failed[0][1]
        if self.on_error == "stream":
            fault_id = "!" + self.stream_id
            junction = self.app.junctions.get(fault_id)
            if junction is not None and evs:
                fault_events = []
                for e, x, _ in failed:
                    if e is not None:
                        fault_events.append(
                            ev.Event(e.timestamp, list(e.data) + [repr(x)]))
                self.app._route(fault_id, fault_events)
                return
        elif self.on_error == "store":
            store = getattr(self.app, "error_store", None)
            if store is not None and evs:
                store.store(self.stream_id, evs, exc, origin="sink")
                return
        for _, _, conn in failed:
            conn.dropped_total += 1
        log.error("sink for %r dropped %d event(s) after transport "
                  "failure (on.error=%r): %r", self.stream_id,
                  len(failed), self.on_error, exc)
