"""Sinks: egress with mappers, log sink, distributed publishing strategies
(reference: CORE/stream/output/sink/Sink.java:59, LogSink.java,
InMemorySink.java:115, distributed/RoundRobin:99 + Partitioned:111).
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

from ..core import event as ev
from .broker import InMemoryBroker
from .mappers import SINK_MAPPERS, SinkMapper

log = logging.getLogger("siddhi_tpu")


class Sink:
    """Transport SPI: subclass and register with register_sink_type.

    `self.config_reader` (scoped to `sink.<type>.*`) is injected before
    init — reference: Sink.init receives a ConfigReader
    (CORE/stream/output/sink/Sink.java:59 via DefinitionParserHelper)."""

    config_reader = None

    def init(self, options: Dict[str, Any]):
        self.options = options

    def connect(self) -> None:
        pass

    def disconnect(self) -> None:
        pass

    def publish(self, payload: Any) -> None:
        raise NotImplementedError


class InMemorySink(Sink):
    def publish(self, payload):
        InMemoryBroker.publish(self.options.get("topic"), payload)


class LogSink(Sink):
    """reference: CORE/stream/output/sink/LogSink.java:194"""

    def publish(self, payload):
        prefix = self.options.get("prefix", "")
        log.info("%s%s", prefix + (" : " if prefix else ""), payload)


def _stable_hash(v) -> int:
    """Deterministic partition hash: Python's hash() is salted per process
    (PYTHONHASHSEED), which would route the same key to different
    @destination endpoints across sender processes/restarts — fatal for
    cross-host sharded pipelines (reference: PartitionedTransport routes on
    a stable key hash)."""
    import zlib
    return zlib.crc32(repr(v).encode())


SINK_TYPES: Dict[str, type] = {"inMemory": InMemorySink, "log": LogSink}


def register_sink_type(name: str, cls: type) -> None:
    SINK_TYPES[name] = cls


class DistributionStrategy:
    """@distribution strategy SPI (reference: distributed/
    DistributionStrategy.java — RoundRobin:99 / Partitioned:111 in core;
    custom strategies register with @distribution_strategy or
    setExtension).  One instance per distributed sink."""

    def init(self, schema, dist_ann, n_destinations: int) -> None:
        self.schema = schema
        self.ann = dist_ann
        self.n = n_destinations

    def destination(self, event, payload) -> int:
        """Destination index in [0, n) for one event/payload."""
        raise NotImplementedError


class RoundRobinStrategy(DistributionStrategy):
    """Cycles destinations (reference: RoundRobinStrategy.java:99)."""

    def init(self, schema, dist_ann, n_destinations):
        super().init(schema, dist_ann, n_destinations)
        self._rr = 0

    def destination(self, event, payload):
        i = self._rr % self.n
        self._rr += 1
        return i


class PartitionedStrategy(DistributionStrategy):
    """Stable-hash routing on partitionKey (reference:
    PartitionedStrategy.java:111)."""

    def init(self, schema, dist_ann, n_destinations):
        super().init(schema, dist_ann, n_destinations)
        key = dist_ann.element("partitionKey")
        if key is None:
            raise ValueError("partitioned distribution needs partitionKey=")
        self._pos = schema.position(key)

    def destination(self, event, payload):
        if event is None:
            raise ValueError(
                "partitioned distribution needs a 1:1 sink mapper (the "
                "mapper emitted a different payload count, so payloads "
                "cannot be matched to their events' partition keys)")
        return _stable_hash(event.data[self._pos]) % self.n


DIST_STRATEGIES: Dict[str, type] = {
    "roundrobin": RoundRobinStrategy,
    "partitioned": PartitionedStrategy,
}


class SinkRuntime:
    """Wires one @sink annotation: stream events -> mapper -> transport(s).

    `@sink(..., @distribution(strategy='roundRobin'|'partitioned',
    partitionKey='attr', @destination(topic='t1'), @destination(topic='t2')))`
    publishes across destinations (reference: DistributedTransport + its
    RoundRobin/Partitioned strategies)."""

    def __init__(self, stream_id: str, ann, app):
        self.stream_id = stream_id
        self.app = app
        stype = ann.element("type") or ann.element(None)
        if stype is None:
            raise ValueError(f"@sink on {stream_id!r} needs type=")
        if stype not in SINK_TYPES:
            raise ValueError(
                f"unknown sink type {stype!r}; registered: "
                f"{sorted(SINK_TYPES)}")
        self.options = ann.named_elements()
        map_ann = dist_ann = None
        for sub in ann.annotations:
            n = sub.name.lower()
            if n == "map":
                map_ann = sub
            elif n == "distribution":
                dist_ann = sub
        mtype = (map_ann.element("type") if map_ann else None) or \
            "passThrough"
        if mtype not in SINK_MAPPERS:
            raise ValueError(f"unknown sink map type {mtype!r}")
        schema = app.schemas[stream_id]
        self.mapper: SinkMapper = SINK_MAPPERS[mtype](schema, map_ann)

        self.sinks: List[Sink] = []
        self.strategy: Optional[DistributionStrategy] = None
        if dist_ann is not None:
            sname = str(dist_ann.element("strategy") or "roundRobin")
            scls = DIST_STRATEGIES.get(sname.lower())
            if scls is None:
                raise ValueError(
                    f"unknown distribution strategy {sname!r}; registered: "
                    f"{sorted(DIST_STRATEGIES)}")
            self.strategy = scls()
            for dest in dist_ann.annotations:
                if dest.name.lower() == "destination":
                    opts = dict(self.options)
                    opts.update(dest.named_elements())
                    s = SINK_TYPES[stype]()
                    s.config_reader = \
                        app.config_manager.generate_config_reader(
                            "sink", str(stype))
                    s.init(opts)
                    self.sinks.append(s)
            if not self.sinks:
                raise ValueError("@distribution needs @destination(...)s")
            self.strategy.init(schema, dist_ann, len(self.sinks))
        else:
            s = SINK_TYPES[stype]()
            s.config_reader = app.config_manager.generate_config_reader(
                "sink", str(stype))
            s.init(self.options)
            self.sinks.append(s)

    def start(self) -> None:
        for s in self.sinks:
            s.connect()

    def stop(self) -> None:
        for s in self.sinks:
            s.disconnect()

    # StreamCallback entry
    def __call__(self, events: List[ev.Event]) -> None:
        stats = self.app.stats
        if not stats.enabled:
            self._flush(events)
            return
        from ..observability import tracing as _tracing
        t0 = time.perf_counter_ns()
        try:
            if _tracing.active() is not None:
                with _tracing.span("sink", stream=self.stream_id,
                                   events=len(events)):
                    self._flush(events)
            else:
                self._flush(events)
        finally:
            stats.sink_latency(self.stream_id, time.perf_counter_ns() - t0)

    def _flush(self, events: List[ev.Event]) -> None:
        payloads = self.mapper.map(events)
        if self.strategy is None or len(self.sinks) == 1:
            for p in payloads:
                self.sinks[0].publish(p)
            return
        if len(payloads) == len(events):
            pairs = zip(events, payloads)
        else:
            # a custom mapper emitted N payloads per event: every payload
            # still publishes; event-based strategies (partitioned) get
            # event=None and must reject it rather than drop data
            pairs = ((None, p) for p in payloads)
        for e, p in pairs:
            self.sinks[self.strategy.destination(e, p)
                       % len(self.sinks)].publish(p)
