"""Sinks: egress with mappers, log sink, distributed publishing strategies
(reference: CORE/stream/output/sink/Sink.java:59, LogSink.java,
InMemorySink.java:115, distributed/RoundRobin:99 + Partitioned:111).
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List

from ..core import event as ev
from .broker import InMemoryBroker
from .mappers import SINK_MAPPERS, SinkMapper

log = logging.getLogger("siddhi_tpu")


class Sink:
    """Transport SPI: subclass and register with register_sink_type.

    `self.config_reader` (scoped to `sink.<type>.*`) is injected before
    init — reference: Sink.init receives a ConfigReader
    (CORE/stream/output/sink/Sink.java:59 via DefinitionParserHelper)."""

    config_reader = None

    def init(self, options: Dict[str, Any]):
        self.options = options

    def connect(self) -> None:
        pass

    def disconnect(self) -> None:
        pass

    def publish(self, payload: Any) -> None:
        raise NotImplementedError


class InMemorySink(Sink):
    def publish(self, payload):
        InMemoryBroker.publish(self.options.get("topic"), payload)


class LogSink(Sink):
    """reference: CORE/stream/output/sink/LogSink.java:194"""

    def publish(self, payload):
        prefix = self.options.get("prefix", "")
        log.info("%s%s", prefix + (" : " if prefix else ""), payload)


def _stable_hash(v) -> int:
    """Deterministic partition hash: Python's hash() is salted per process
    (PYTHONHASHSEED), which would route the same key to different
    @destination endpoints across sender processes/restarts — fatal for
    cross-host sharded pipelines (reference: PartitionedTransport routes on
    a stable key hash)."""
    import zlib
    return zlib.crc32(repr(v).encode())


SINK_TYPES: Dict[str, type] = {"inMemory": InMemorySink, "log": LogSink}


def register_sink_type(name: str, cls: type) -> None:
    SINK_TYPES[name] = cls


class SinkRuntime:
    """Wires one @sink annotation: stream events -> mapper -> transport(s).

    `@sink(..., @distribution(strategy='roundRobin'|'partitioned',
    partitionKey='attr', @destination(topic='t1'), @destination(topic='t2')))`
    publishes across destinations (reference: DistributedTransport + its
    RoundRobin/Partitioned strategies)."""

    def __init__(self, stream_id: str, ann, app):
        self.stream_id = stream_id
        self.app = app
        stype = ann.element("type") or ann.element(None)
        if stype is None:
            raise ValueError(f"@sink on {stream_id!r} needs type=")
        if stype not in SINK_TYPES:
            raise ValueError(
                f"unknown sink type {stype!r}; registered: "
                f"{sorted(SINK_TYPES)}")
        self.options = ann.named_elements()
        map_ann = dist_ann = None
        for sub in ann.annotations:
            n = sub.name.lower()
            if n == "map":
                map_ann = sub
            elif n == "distribution":
                dist_ann = sub
        mtype = (map_ann.element("type") if map_ann else None) or \
            "passThrough"
        if mtype not in SINK_MAPPERS:
            raise ValueError(f"unknown sink map type {mtype!r}")
        schema = app.schemas[stream_id]
        self.mapper: SinkMapper = SINK_MAPPERS[mtype](schema, map_ann)

        self.sinks: List[Sink] = []
        self.strategy = None
        self.partition_positions = None
        self._rr = 0
        if dist_ann is not None:
            self.strategy = (dist_ann.element("strategy") or
                             "roundRobin")
            key = dist_ann.element("partitionKey")
            if self.strategy == "partitioned":
                if key is None:
                    raise ValueError(
                        "partitioned distribution needs partitionKey=")
                self.partition_positions = schema.position(key)
            for dest in dist_ann.annotations:
                if dest.name.lower() == "destination":
                    opts = dict(self.options)
                    opts.update(dest.named_elements())
                    s = SINK_TYPES[stype]()
                    s.config_reader = \
                        app.config_manager.generate_config_reader(
                            "sink", str(stype))
                    s.init(opts)
                    self.sinks.append(s)
            if not self.sinks:
                raise ValueError("@distribution needs @destination(...)s")
        else:
            s = SINK_TYPES[stype]()
            s.config_reader = app.config_manager.generate_config_reader(
                "sink", str(stype))
            s.init(self.options)
            self.sinks.append(s)

    def start(self) -> None:
        for s in self.sinks:
            s.connect()

    def stop(self) -> None:
        for s in self.sinks:
            s.disconnect()

    # StreamCallback entry
    def __call__(self, events: List[ev.Event]) -> None:
        payloads = self.mapper.map(events)
        if self.strategy is None or len(self.sinks) == 1:
            for p in payloads:
                self.sinks[0].publish(p)
            return
        if self.strategy == "roundRobin":
            for p in payloads:
                self.sinks[self._rr % len(self.sinks)].publish(p)
                self._rr += 1
        else:  # partitioned
            for e, p in zip(events, payloads):
                v = e.data[self.partition_positions]
                self.sinks[_stable_hash(v) % len(self.sinks)].publish(p)
