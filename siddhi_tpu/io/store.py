"""Record table stores: the external-storage SPI behind `@store(...)` tables,
plus the bounded row cache (FIFO/LRU/LFU).

Reference behavior (what): CORE/table/record/AbstractRecordTable.java:449
(connect-with-retry, add/find/contains/delete/update/updateOrAdd against an
external store), CORE/table/CacheTable.java:62 with FIFO:111/LRU:128/LFU:128
policies, and the `@store` annotation consumed by DefinitionParserHelper.

TPU-native design (how): external stores are host-side I/O, so the SPI is a
plain Python class registered with @record_store("type").  The streaming hot
path never talks to the store row-by-row: the runtime keeps the store's rows
mirrored in the device-resident columnar table (joins and filters stay on
the TPU), and write operations flow through the store SPI so the external
system stays authoritative.  Conditions hand stores BOTH the expression AST
(for query pushdown, e.g. SQL translation) and a host row predicate.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..query_api.expression import (
    Add,
    And,
    AttributeFunction,
    Compare,
    Constant,
    Divide,
    Expression,
    IsNull,
    Mod,
    Multiply,
    Not,
    Or,
    Subtract,
    Variable,
)

_STORE_TYPES: Dict[str, type] = {}


def record_store(name: str):
    """Register a RecordTable store type (reference: @Extension store types,
    e.g. store:rdbms)."""
    def deco(cls):
        _STORE_TYPES[name.lower()] = cls
        return cls
    return deco


def store_registry() -> Dict[str, type]:
    return _STORE_TYPES


def create_store(type_name: str, table_def, schema, properties: Dict,
                 config_reader=None) -> "RecordTable":
    cls = _STORE_TYPES.get(type_name.lower())
    if cls is None:
        raise ValueError(
            f"unknown store type {type_name!r}; registered: "
            f"{sorted(_STORE_TYPES)}")
    store = cls()
    store.init(table_def, schema, properties, config_reader)
    return store


class StoreCondition:
    """Condition handed to stores: the raw AST for pushdown plus a compiled
    host predicate fn(table_row: tuple, params: dict) -> bool."""

    def __init__(self, ast: Optional[Expression], schema, other_key=None):
        self.ast = ast
        self.schema = schema
        self.other_key = other_key
        self._fn = _compile_host(ast, schema, other_key) if ast is not None \
            else (lambda row, params: True)

    def matches(self, row: Sequence, params: Optional[Dict] = None) -> bool:
        return bool(self._fn(row, params or {}))


def _compile_host(expr: Expression, schema, other_key):
    """Expression AST -> python predicate over one table row.  Variables of
    the table schema read the row; `other_key`-qualified (or unresolved)
    variables read the params dict."""
    pos = {a.name: i for i, a in enumerate(schema.definition.attribute_list)}

    def ev_(e, row, params):
        if isinstance(e, Constant):
            return e.value
        if isinstance(e, Variable):
            n = e.attribute_name
            if e.stream_id in (None, schema.definition.id) and n in pos:
                return row[pos[n]]
            return params.get(f"{e.stream_id}.{n}" if e.stream_id else n,
                              params.get(n))
        if isinstance(e, Compare):
            l, r = ev_(e.left, row, params), ev_(e.right, row, params)
            return {"<": l < r, "<=": l <= r, ">": l > r, ">=": l >= r,
                    "==": l == r, "!=": l != r}[e.operator]
        if isinstance(e, And):
            return ev_(e.left, row, params) and ev_(e.right, row, params)
        if isinstance(e, Or):
            return ev_(e.left, row, params) or ev_(e.right, row, params)
        if isinstance(e, Not):
            return not ev_(e.expression, row, params)
        if isinstance(e, Add):
            return ev_(e.left, row, params) + ev_(e.right, row, params)
        if isinstance(e, Subtract):
            return ev_(e.left, row, params) - ev_(e.right, row, params)
        if isinstance(e, Multiply):
            return ev_(e.left, row, params) * ev_(e.right, row, params)
        if isinstance(e, Divide):
            return ev_(e.left, row, params) / ev_(e.right, row, params)
        if isinstance(e, Mod):
            return ev_(e.left, row, params) % ev_(e.right, row, params)
        if isinstance(e, IsNull):
            return ev_(e.expression, row, params) is None
        if isinstance(e, AttributeFunction):
            raise ValueError(
                f"function {e.name!r} not supported in store conditions")
        raise ValueError(f"unsupported store condition node {e!r}")

    return lambda row, params: ev_(expr, row, params)


from ..exceptions import ConnectionUnavailableException  # noqa: E402


class RecordTable:
    """Store SPI (reference: AbstractRecordTable.java:449).

    Lifecycle: init -> connect (with exponential-backoff retry) -> add/
    find/delete_rows/update_rows/read_all -> disconnect."""

    def init(self, table_def, schema, properties: Dict,
             config_reader=None) -> None:
        self.table_def = table_def
        self.schema = schema
        self.properties = properties
        self.config_reader = config_reader

    def connect(self) -> None:
        pass

    def disconnect(self) -> None:
        pass

    # -- record operations ----------------------------------------------------
    def add(self, records: List[Tuple]) -> None:
        raise NotImplementedError

    def read_all(self) -> List[Tuple]:
        raise NotImplementedError

    def find(self, condition: StoreCondition,
             params: Optional[Dict] = None) -> List[Tuple]:
        return [r for r in self.read_all() if condition.matches(r, params)]

    def contains(self, condition: StoreCondition,
                 params: Optional[Dict] = None) -> bool:
        return bool(self.find(condition, params))

    def delete_rows(self, rows: List[Tuple],
                    condition: Optional[StoreCondition] = None) -> None:
        raise NotImplementedError

    def update_rows(self, old_rows: List[Tuple], new_rows: List[Tuple],
                    condition: Optional[StoreCondition] = None) -> None:
        raise NotImplementedError


def connect_with_retry(store: RecordTable, name: str,
                       max_wait_s: float = 60.0,
                       max_attempts: int = 20,
                       _sleep=time.sleep) -> None:
    """Exponential backoff connect (reference: BackoffRetryCounter sequence
    5s,10s,...,1min capped).  Bounded: after `max_attempts` failures the
    last ConnectionUnavailableException propagates — an unreachable store
    must fail the app start, not hang its thread forever."""
    wait = 0.05
    for attempt in range(max_attempts):
        try:
            store.connect()
            return
        except ConnectionUnavailableException:
            if attempt == max_attempts - 1:
                raise
            _sleep(wait)
            wait = min(wait * 2, max_wait_s)


@record_store("memory")
class InMemoryRecordStore(RecordTable):
    """In-process list-of-rows store: the test double for all record-table
    behavior (reference: TEST/query/table/util/TestStore)."""

    def init(self, table_def, schema, properties, config_reader=None):
        super().init(table_def, schema, properties, config_reader)
        self.rows: List[Tuple] = []
        self._lock = threading.Lock()

    def add(self, records):
        with self._lock:
            self.rows.extend(tuple(r) for r in records)

    def read_all(self):
        with self._lock:
            return list(self.rows)

    def delete_rows(self, rows, condition=None):
        with self._lock:
            for r in rows:
                try:
                    self.rows.remove(tuple(r))
                except ValueError:
                    pass

    def update_rows(self, old_rows, new_rows, condition=None):
        with self._lock:
            for old, new in zip(old_rows, new_rows):
                try:
                    i = self.rows.index(tuple(old))
                    self.rows[i] = tuple(new)
                except ValueError:
                    self.rows.append(tuple(new))


# ---------------------------------------------------------------------------
# Cache layer (reference: CacheTable + FIFO/LRU/LFU policies)
# ---------------------------------------------------------------------------


class CachePolicy:
    """Bounded key->row cache; subclasses choose the eviction victim."""

    def __init__(self, max_size: int):
        self.max_size = max_size
        self._rows: Dict[Any, Tuple] = {}

    def __len__(self):
        return len(self._rows)

    def __contains__(self, key):
        return key in self._rows

    def get(self, key):
        row = self._rows.get(key)
        if row is not None:
            self._touch(key)
        return row

    def put(self, key, row) -> None:
        if key not in self._rows and len(self._rows) >= self.max_size:
            victim = self._victim()
            if victim is not None:
                self.evict(victim)
        self._rows[key] = row
        self._admit(key)

    def evict(self, key) -> None:
        self._rows.pop(key, None)
        self._forget(key)

    def clear(self) -> None:
        self._rows.clear()

    # policy hooks
    def _admit(self, key) -> None: ...
    def _touch(self, key) -> None: ...
    def _forget(self, key) -> None: ...
    def _victim(self): ...


class FIFOCache(CachePolicy):
    """Evict the oldest-admitted entry (reference: CacheTableFIFO)."""

    def __init__(self, max_size):
        super().__init__(max_size)
        self._order: List[Any] = []

    def _admit(self, key):
        if key not in self._order:
            self._order.append(key)

    def _forget(self, key):
        if key in self._order:
            self._order.remove(key)

    def _victim(self):
        return self._order[0] if self._order else None


class LRUCache(CachePolicy):
    """Evict the least-recently-used entry (reference: CacheTableLRU)."""

    def __init__(self, max_size):
        super().__init__(max_size)
        self._stamp: Dict[Any, int] = {}
        self._tick = 0

    def _admit(self, key):
        self._touch(key)

    def _touch(self, key):
        self._tick += 1
        self._stamp[key] = self._tick

    def _forget(self, key):
        self._stamp.pop(key, None)

    def _victim(self):
        return min(self._stamp, key=self._stamp.get) if self._stamp else None


class LFUCache(CachePolicy):
    """Evict the least-frequently-used entry (reference: CacheTableLFU)."""

    def __init__(self, max_size):
        super().__init__(max_size)
        self._hits: Dict[Any, int] = {}

    def _admit(self, key):
        self._hits.setdefault(key, 0)

    def _touch(self, key):
        self._hits[key] = self._hits.get(key, 0) + 1

    def _forget(self, key):
        self._hits.pop(key, None)

    def _victim(self):
        return min(self._hits, key=self._hits.get) if self._hits else None


CACHE_POLICIES = {"FIFO": FIFOCache, "LRU": LRUCache, "LFU": LFUCache}


class CacheTable:
    """Bounded read cache in front of a RecordTable (reference:
    CacheTable.java:62).  Keys are the table's primary key tuples."""

    def __init__(self, store: RecordTable, key_positions: List[int],
                 max_size: int = 10, policy: str = "FIFO",
                 preload: bool = False):
        if policy.upper() not in CACHE_POLICIES:
            raise ValueError(
                f"unknown cache policy {policy!r}; one of FIFO/LRU/LFU")
        self.store = store
        self.key_positions = key_positions
        self.cache: CachePolicy = CACHE_POLICIES[policy.upper()](max_size)
        self.hits = 0
        self.misses = 0
        if preload:
            for row in store.read_all()[:max_size]:
                self.cache.put(self._key(row), row)

    def _key(self, row):
        return tuple(row[i] for i in self.key_positions)

    def get(self, key_values: Tuple) -> Optional[Tuple]:
        row = self.cache.get(key_values)
        if row is not None:
            self.hits += 1
            return row
        self.misses += 1
        cond = StoreCondition(None, None)
        for r in self.store.read_all():
            if self._key(r) == key_values:
                self.cache.put(key_values, r)
                return r
        return None

    def on_add(self, rows: List[Tuple]) -> None:
        for r in rows:
            self.cache.put(self._key(r), r)

    def on_delete(self, rows: List[Tuple]) -> None:
        for r in rows:
            self.cache.evict(self._key(r))

    def on_update(self, old_rows: List[Tuple], new_rows: List[Tuple]) -> None:
        for o, n in zip(old_rows, new_rows):
            self.cache.evict(self._key(o))
            self.cache.put(self._key(n), n)
