"""Sink/source resilience: backoff, connection state machine, breaker.

Reference (what): the reference treats I/O failure as a first-class
state machine — `ConnectionUnavailableException` triggers
backoff-driven reconnect loops (Source.connectWithRetry :155-169 +
BackoffRetryCounter), and `@sink(on.error=...)` selects a per-transport
recovery policy (Sink.onError: RETRY blocks-and-redials, WAIT
backpressures the caller, LOG drops loudly, STREAM routes into the
`!stream` fault stream).

TPU design (how): the engine fronts a remote accelerator, so a sink
stall must never stall the dispatch path longer than the caller asked
for.  One `SinkConnection` wraps each transport with a
CONNECTED/RETRYING/BROKEN state machine:

- **CONNECTED**: publishes go straight to the transport.
- **RETRYING** (`on.error='retry'`): failed + subsequent payloads land
  in a bounded in-flight buffer while a background thread redials with
  exponential backoff + jitter, then re-publishes the buffer in order
  (zero loss when the transport recovers within the buffer bound).
- **BROKEN**: after `breaker.failures` consecutive failures the circuit
  trips; load is shed immediately (no buffering, no blocking) until a
  half-open probe — the next reconnect attempt, paced at the probe
  interval — succeeds.

`on.error='wait'` retries on the CALLER's thread with the same backoff
up to a deadline (backpressure, reference WAIT semantics); 'log',
'stream', and 'store' attempt once and let SinkRuntime route the failed
events (log-and-drop, `!stream` fault path, error store).

Clock and sleep are injectable so tests drive the machine with a fake
clock — CI never depends on real backoff sleeps.
"""
from __future__ import annotations

import logging
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from ..exceptions import ConnectionUnavailableError

log = logging.getLogger("siddhi_tpu")

# connection states (stable API: health/metrics expose these strings)
CONNECTED = "CONNECTED"
RETRYING = "RETRYING"
BROKEN = "BROKEN"

_STATE_GAUGE = {CONNECTED: 0, RETRYING: 1, BROKEN: 2}

# sink on.error policies (reference: Sink.OnErrorAction + error store)
SINK_POLICIES = ("log", "retry", "wait", "stream", "store")


def state_gauge(state: str) -> int:
    """Numeric encoding for the siddhi_sink_breaker_state gauge."""
    return _STATE_GAUGE.get(state, 1)


class BackoffPolicy:
    """Exponential backoff with full-jitter cap (reference:
    BackoffRetryCounter's geometric sequence; jitter added so a fleet of
    reconnecting sinks doesn't thundering-herd a recovering broker)."""

    def __init__(self, initial_s: float = 0.1, multiplier: float = 2.0,
                 max_s: float = 5.0, jitter: float = 0.25,
                 rng: Optional[random.Random] = None):
        self.initial_s = max(1e-4, float(initial_s))
        self.multiplier = max(1.0, float(multiplier))
        self.max_s = max(self.initial_s, float(max_s))
        self.jitter = min(1.0, max(0.0, float(jitter)))
        self.rng = rng or random.Random()

    def delay(self, attempt: int) -> float:
        """Delay before retry number `attempt` (0-based), jittered."""
        base = min(self.initial_s * self.multiplier ** max(0, attempt),
                   self.max_s)
        if not self.jitter:
            return base
        return base * (1.0 - self.jitter * self.rng.random())

    @classmethod
    def from_options(cls, options: Dict[str, Any],
                     rng: Optional[random.Random] = None) -> "BackoffPolicy":
        """Build from @sink/@source annotation options (ms-denominated,
        matching the reference's *.ms config keys):
        retry.initial.ms / retry.multiplier / retry.max.ms /
        retry.jitter."""
        return cls(
            initial_s=float(options.get("retry.initial.ms", 100)) / 1e3,
            multiplier=float(options.get("retry.multiplier", 2.0)),
            max_s=float(options.get("retry.max.ms", 5000)) / 1e3,
            jitter=float(options.get("retry.jitter", 0.25)),
            rng=rng)


class SinkConnection:
    """State machine wrapping ONE transport Sink (one per @destination).

    Only `ConnectionUnavailableError` drives the machine — an
    application bug raised by a transport must not trip the breaker or
    start redial loops.  All mutation happens under `_lock`; `state`,
    `retries_total`, and `dropped_total` are read lock-free by the
    metrics/health scrape path."""

    def __init__(self, sink, stream_id: str = "", policy: str = "log",
                 backoff: Optional[BackoffPolicy] = None,
                 buffer_size: int = 1024, breaker_failures: int = 5,
                 wait_timeout_s: float = 30.0,
                 probe_interval_s: Optional[float] = None,
                 on_drop: Optional[Callable[[Any, str], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        if policy not in SINK_POLICIES:
            raise ValueError(
                f"unknown on.error policy {policy!r}; one of "
                f"{SINK_POLICIES}")
        self.sink = sink
        self.stream_id = stream_id
        self.policy = policy
        self.backoff = backoff or BackoffPolicy()
        self.buffer_size = max(1, int(buffer_size))
        self.breaker_failures = max(1, int(breaker_failures))
        self.wait_timeout_s = float(wait_timeout_s)
        self.probe_interval_s = float(
            probe_interval_s if probe_interval_s is not None
            else self.backoff.max_s)
        self.on_drop = on_drop
        self._clock = clock

        self.state = CONNECTED
        self.retries_total = 0
        self.dropped_total = 0
        self.published_total = 0
        self._consecutive = 0
        self._next_probe = 0.0
        self._buffer: deque = deque()
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------
    def connect(self) -> None:
        self._stop.clear()
        try:
            self.sink.connect()
            self.state = CONNECTED
        except ConnectionUnavailableError as exc:
            # start degraded: retry policy redials in the background,
            # the rest reconnect lazily on the next publish
            log.warning("sink for %r failed to connect (%r); will retry",
                        self.stream_id, exc)
            with self._lock:
                self.state = RETRYING
                if self.policy == "retry":
                    self._ensure_worker()

    def close(self) -> None:
        self._stop.set()
        w = self._worker
        if w is not None:
            w.join(timeout=2.0)
        with self._lock:
            n = len(self._buffer)
            self._buffer.clear()
        if n:
            self._count_drop(None, "shutdown", n)
        try:
            self.sink.disconnect()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass

    def buffered(self) -> int:
        with self._lock:
            return len(self._buffer)

    # -- publish ---------------------------------------------------------------
    def publish(self, payload: Any) -> None:
        """Publish one payload under this connection's policy.  Raises
        ConnectionUnavailableError only when the policy hands the
        failure back to the caller: 'log'/'stream'/'store' after their
        single attempt (SinkRuntime routes the events), 'wait' after
        its deadline, and any policy while the breaker is open."""
        if self.policy == "retry":
            self._publish_retry(payload)
            return
        if self.state == BROKEN and self._clock() < self._next_probe:
            raise ConnectionUnavailableError(
                f"sink for {self.stream_id!r} circuit open "
                f"({self._consecutive} consecutive failures); next "
                f"half-open probe in "
                f"{self._next_probe - self._clock():.2f}s")
        try:
            self._attempt(payload)
        except ConnectionUnavailableError:
            if self.policy == "wait":
                self._publish_wait(payload)
            else:
                raise

    def _attempt(self, payload: Any) -> None:
        """One transport attempt; success/failure drives the machine."""
        try:
            self.sink.publish(payload)
        except ConnectionUnavailableError:
            self._on_failure()
            raise
        with self._lock:
            self.published_total += 1
            self._consecutive = 0
            self.state = CONNECTED

    def _on_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if self._consecutive >= self.breaker_failures:
                if self.state != BROKEN:
                    log.error(
                        "sink for %r: circuit BROKEN after %d consecutive "
                        "failures; shedding load (half-open probe every "
                        "%.1fs)", self.stream_id, self._consecutive,
                        self.probe_interval_s)
                self.state = BROKEN
                self._next_probe = self._clock() + self.probe_interval_s
            elif self.state == CONNECTED:
                self.state = RETRYING

    # -- wait policy (caller-thread backpressure) ------------------------------
    def _sleep(self, delay: float) -> bool:
        """Interruptible sleep; True = shutting down.  Tests monkeypatch
        this (or `_clock`) with a fake clock for determinism."""
        return self._stop.wait(delay)

    def _publish_wait(self, payload: Any) -> None:
        deadline = self._clock() + self.wait_timeout_s
        attempt = 0
        while True:
            remaining = deadline - self._clock()
            if remaining <= 0:
                raise ConnectionUnavailableError(
                    f"sink for {self.stream_id!r} unavailable after "
                    f"blocking {self.wait_timeout_s:.1f}s "
                    f"(on.error='wait' deadline)")
            if self._sleep(min(self.backoff.delay(attempt), remaining)):
                raise ConnectionUnavailableError(
                    f"sink for {self.stream_id!r} shut down while a "
                    "publish was blocked in on.error='wait'")
            with self._lock:
                self.retries_total += 1
            try:
                self._reconnect()
                self._attempt(payload)
                return
            except ConnectionUnavailableError:
                attempt += 1

    # -- retry policy (background redial + ordered replay) ---------------------
    def _publish_retry(self, payload: Any) -> None:
        with self._lock:
            if self.state == BROKEN:
                # shed unless the half-open probe is due; the probe is
                # the worker's next redial, so just wake it via buffer
                if self._clock() < self._next_probe:
                    self._count_drop(payload, "breaker-open", 1)
                    return
                self._buffer_or_drop(payload)
                self._ensure_worker()
                return
            if self.state == RETRYING:
                # keep publish order: never overtake buffered payloads
                self._buffer_or_drop(payload)
                self._ensure_worker()
                return
        try:
            self._attempt(payload)
        except ConnectionUnavailableError:
            with self._lock:
                self._buffer_or_drop(payload)
                self._ensure_worker()

    def _buffer_or_drop(self, payload: Any) -> None:
        if len(self._buffer) >= self.buffer_size:
            self._count_drop(payload, "buffer-full", 1)
            return
        self._buffer.append(payload)

    def _count_drop(self, payload: Any, reason: str, n: int) -> None:
        self.dropped_total += n
        if self.on_drop is not None:
            try:
                self.on_drop(payload, reason)
            except Exception:  # noqa: BLE001 — drop hook must not throw
                pass
        else:
            log.warning("sink for %r dropped %d payload(s): %s",
                        self.stream_id, n, reason)

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        self._worker = threading.Thread(
            target=self._redial_loop, daemon=True,
            name=f"sink-retry-{self.stream_id}")
        self._worker.start()

    def _reconnect(self) -> None:
        """Drop the (presumed dead) transport session and dial fresh."""
        try:
            self.sink.disconnect()
        except Exception:  # noqa: BLE001 — dead transports throw freely
            pass
        self.sink.connect()

    def _redial_loop(self) -> None:
        attempt = 0
        while not self._stop.is_set():
            delay = self.probe_interval_s if self.state == BROKEN \
                else self.backoff.delay(attempt)
            if self._sleep(delay):
                return
            with self._lock:
                self.retries_total += 1
            try:
                self._reconnect()
                # replay the in-flight buffer IN ORDER; a failure mid-
                # drain leaves the remainder buffered for the next round
                while True:
                    with self._lock:
                        if not self._buffer:
                            break
                        head = self._buffer[0]
                    self.sink.publish(head)
                    with self._lock:
                        self._buffer.popleft()
                        self.published_total += 1
                with self._lock:
                    self._consecutive = 0
                    if self.state != CONNECTED:
                        log.info("sink for %r reconnected after %d "
                                 "redial(s)", self.stream_id, attempt + 1)
                    self.state = CONNECTED
                return
            except ConnectionUnavailableError:
                attempt += 1
                self._on_failure()
