"""I/O layer: sources, sinks, mappers, in-memory broker.

Reference: CORE/stream/input/source/*, CORE/stream/output/sink/*,
CORE/util/transport/InMemoryBroker.java.
"""
from .broker import InMemoryBroker
from .errorstore import ErrorStore, InMemoryErrorStore
from .mappers import SINK_MAPPERS, SOURCE_MAPPERS
from .resilience import BackoffPolicy, SinkConnection
from .sink import SinkRuntime, register_sink_type
from .source import SourceRuntime, register_source_type
from . import tcp as _tcp  # registers the 'tcp' source/sink transport pair

__all__ = [
    "InMemoryBroker",
    "SourceRuntime",
    "SinkRuntime",
    "SOURCE_MAPPERS",
    "SINK_MAPPERS",
    "register_source_type",
    "register_sink_type",
    "BackoffPolicy",
    "SinkConnection",
    "ErrorStore",
    "InMemoryErrorStore",
]
