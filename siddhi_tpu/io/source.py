"""Sources: transport-agnostic ingestion with connect-retry and
pause/resume (reference: CORE/stream/input/source/Source.java:50 —
connectWithRetry :155-169, BackoffRetryCounter, InMemorySource.java:63).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

from . import broker as _broker
from .mappers import SOURCE_MAPPERS, SourceMapper
from .resilience import BackoffPolicy


class Source:
    """Transport SPI: subclass and register with @source_extension.

    `self.config_reader` (a utils.config.ConfigReader scoped to
    `source.<type>.*`) is injected before init — the reference hands the
    reader to Source.init (CORE/stream/input/source/Source.java:66 via
    DefinitionParserHelper); here it rides the instance so subclass init
    signatures stay stable."""

    config_reader = None

    def init(self, options: Dict[str, Any], deliver: Callable[[Any], None]):
        """`deliver(payload)` pushes one transport payload into the mapper."""
        self.options = options
        self.deliver = deliver

    def connect(self) -> None:
        pass

    def disconnect(self) -> None:
        pass

    def pause(self) -> None:
        pass

    def resume(self) -> None:
        pass


class InMemorySource(Source):
    """reference: CORE/stream/input/source/InMemorySource.java:63"""

    def connect(self):
        topic = self.options.get("topic")
        if topic is None:
            raise ValueError("inMemory source needs a topic")
        self._sub = _broker.subscribe_fn(topic, self.deliver)

    def disconnect(self):
        if getattr(self, "_sub", None) is not None:
            _broker.InMemoryBroker.unsubscribe(self._sub)
            self._sub = None


SOURCE_TYPES: Dict[str, type] = {"inMemory": InMemorySource}


def register_source_type(name: str, cls: type) -> None:
    SOURCE_TYPES[name] = cls


class SourceRuntime:
    """Wires one @source annotation: transport -> mapper -> stream junction.
    Connection failures retry with exponential backoff + jitter on a
    daemon thread (reference: Source.connectWithRetry +
    BackoffRetryCounter; policy shared with sinks via
    io/resilience.BackoffPolicy).  While disconnected the transport's
    pause() hook is held down so a half-dead source doesn't spin
    delivering into a stream it can no longer feed coherently; resume()
    fires after the reconnect.  Tunables ride the annotation:
    retry.initial.ms / retry.multiplier / retry.max.ms / retry.jitter /
    retry.attempts."""

    def __init__(self, stream_id: str, ann, app):
        self.stream_id = stream_id
        self.app = app
        self.paused = False
        self._pause_cv = threading.Condition()
        self._connected = False
        self._retry_stop = threading.Event()
        self._retry_thread: Optional[threading.Thread] = None

        stype = ann.element("type") or ann.element(None)
        if stype is None:
            raise ValueError(f"@source on {stream_id!r} needs type=")
        if stype not in SOURCE_TYPES:
            raise ValueError(
                f"unknown source type {stype!r}; registered: "
                f"{sorted(SOURCE_TYPES)}")
        self.options = ann.named_elements()
        map_ann = None
        for sub in ann.annotations:
            if sub.name.lower() == "map":
                map_ann = sub
        mtype = (map_ann.element("type") if map_ann else None) or \
            "passThrough"
        if mtype not in SOURCE_MAPPERS:
            raise ValueError(f"unknown source map type {mtype!r}")
        schema = app.schemas[stream_id]
        self.mapper: SourceMapper = SOURCE_MAPPERS[mtype](schema, map_ann)
        self.backoff = BackoffPolicy.from_options(self.options)
        self.retry_attempts = int(self.options.get("retry.attempts", 6))
        self.source: Source = SOURCE_TYPES[stype]()
        self.source.config_reader = app.config_manager.generate_config_reader(
            "source", str(stype))
        self.source.init(self.options, self._deliver)

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> None:
        self._retry_stop.clear()
        try:
            self.source.connect()
            self._connected = True
        except Exception:  # noqa: BLE001 — retry in background
            self._retry_thread = threading.Thread(
                target=self._connect_with_retry, daemon=True,
                name=f"source-retry-{self.stream_id}")
            self._retry_thread.start()

    def _connect_with_retry(self) -> None:
        # hold the TRANSPORT's pause hook (not the runtime gate — that
        # one belongs to persist's quiesce) so a disconnected source
        # doesn't spin-deliver while its backing system is down
        try:
            self.source.pause()
        except Exception:  # noqa: BLE001 — hook is best-effort
            pass
        try:
            for attempt in range(self.retry_attempts):
                if self._retry_stop.wait(self.backoff.delay(attempt)):
                    return
                try:
                    self.source.connect()
                    self._connected = True
                    return
                except Exception:  # noqa: BLE001
                    continue
            import logging
            logging.getLogger("siddhi_tpu").error(
                "source for %r failed to connect after %d retries",
                self.stream_id, self.retry_attempts)
        finally:
            if self._connected:
                try:
                    self.source.resume()
                except Exception:  # noqa: BLE001 — hook is best-effort
                    pass

    def stop(self) -> None:
        self._retry_stop.set()
        if self._retry_thread is not None:
            self._retry_thread.join(timeout=2.0)
            self._retry_thread = None
        self.source.disconnect()
        self._connected = False

    def pause(self) -> None:
        with self._pause_cv:
            self.paused = True
        self.source.pause()

    def resume(self) -> None:
        with self._pause_cv:
            self.paused = False
            self._pause_cv.notify_all()
        self.source.resume()

    # -- data path -------------------------------------------------------------
    def _deliver(self, payload: Any) -> None:
        with self._pause_cv:
            while self.paused:
                self._pause_cv.wait()
        now = self.app.timestamp_millis()
        events = self.mapper.map(payload, now)
        if not events:
            return
        # @source feeds are an EXTERNAL ingest edge exactly like
        # InputHandler sends: the admission rate limit decides them too
        # (block backpressures the transport's delivery thread; shed
        # drops loudly, counted in siddhi_admission_shed_total)
        adm = getattr(self.app, "admission", None)
        if adm is not None and adm.ingest_enabled and \
                not adm.admit_ingest(self.stream_id, len(events)):
            return
        self.app._route(self.stream_id, events)
