"""In-process topic broker (reference: CORE/util/transport/
InMemoryBroker.java:29 — the reference's only built-in "cluster" transport,
connecting apps in the same process)."""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List


class InMemoryBroker:
    _subscribers: Dict[str, List] = {}
    _lock = threading.RLock()

    class Subscriber:
        """Match the reference's subscriber interface: onMessage + topic."""

        def on_message(self, msg: Any) -> None:
            raise NotImplementedError

        def get_topic(self) -> str:
            raise NotImplementedError

    @classmethod
    def subscribe(cls, subscriber) -> None:
        with cls._lock:
            cls._subscribers.setdefault(
                subscriber.get_topic(), []).append(subscriber)

    @classmethod
    def unsubscribe(cls, subscriber) -> None:
        with cls._lock:
            subs = cls._subscribers.get(subscriber.get_topic(), [])
            if subscriber in subs:
                subs.remove(subscriber)

    @classmethod
    def publish(cls, topic: str, msg: Any) -> None:
        with cls._lock:
            subs = list(cls._subscribers.get(topic, []))
        for s in subs:
            s.on_message(msg)

    @classmethod
    def clear(cls) -> None:
        with cls._lock:
            cls._subscribers.clear()


class _FnSubscriber(InMemoryBroker.Subscriber):
    def __init__(self, topic: str, fn: Callable[[Any], None]):
        self._topic = topic
        self._fn = fn

    def on_message(self, msg):
        self._fn(msg)

    def get_topic(self):
        return self._topic


def subscribe_fn(topic: str, fn: Callable[[Any], None]):
    sub = _FnSubscriber(topic, fn)
    InMemoryBroker.subscribe(sub)
    return sub
