"""TCP source/sink transport: the cross-host (DCN) ingress/egress legs.

Reference (what): the reference core ships only in-memory transports; its
inter-process story is the pluggable Source/Sink SPI (SURVEY §5.8 —
Source.java:50, Sink.java:59) with external transport extensions, plus
`@dist` distributed sinks fanning out over multiple endpoints
(DistributedTransport + RoundRobin/Partitioned strategies).

TPU design (how): device-to-device scaling rides the jax.sharding mesh
(ICI collectives); THIS module is the host-side DCN leg that feeds those
meshes from other processes/hosts: a stdlib-socket transport pair speaking
4-byte-length-prefixed JSON frames.  One frame can carry a whole event
batch (a JSON array), so the per-frame overhead amortizes the same way the
runtime's columnar staging does — senders should batch.  Combined with
`@dist(@destination(port=...))` this gives partitioned/round-robin fan-out
across hosts, and with the shardId aggregation mode a multi-host
aggregation pipeline with a store rendezvous.

    @source(type='tcp', port='7071')
    @map(type='json')
    define stream In (k string, v double);

    @sink(type='tcp', host='10.0.0.2', port='7071')
    @map(type='json')
    define stream Out (k string, v double);
"""
from __future__ import annotations

import json
import logging
import socket
import struct
import threading
from typing import Any, List, Optional

from ..exceptions import ConnectionUnavailableError
from .sink import Sink, register_sink_type
from .source import Source, register_source_type

log = logging.getLogger("siddhi_tpu")

_HDR = struct.Struct(">I")
_MAX_FRAME = 64 << 20  # 64 MiB sanity cap


def _send_frame(sock: socket.socket, payload: Any) -> None:
    body = json.dumps(payload).encode()
    sock.sendall(_HDR.pack(len(body)) + body)


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _read_frame(sock: socket.socket) -> Optional[Any]:
    hdr = _read_exact(sock, _HDR.size)
    if hdr is None:
        return None
    (length,) = _HDR.unpack(hdr)
    if length > _MAX_FRAME:
        raise ValueError(f"frame of {length} bytes exceeds cap {_MAX_FRAME}")
    body = _read_exact(sock, length)
    if body is None:
        return None
    return json.loads(body)


class TCPSource(Source):
    """Listens on `port` (and optional `host`), delivers each decoded frame
    to the mapper.  Multiple concurrent client connections are accepted;
    connection failures end that client's reader, the listener stays up."""

    def connect(self) -> None:
        host = self.options.get("host", "0.0.0.0")
        port = int(self.options.get("port", 0))
        try:
            self._srv = socket.create_server((host, port))
        except OSError as exc:
            # typed so SourceRuntime's backoff retry (and tests) can
            # distinguish "port busy / interface down" from a code bug
            raise ConnectionUnavailableError(
                f"tcp source cannot listen on {host}:{port}: "
                f"{exc!r}") from exc
        self._srv.settimeout(0.2)
        self.port = self._srv.getsockname()[1]   # resolved when port=0
        self._stop = threading.Event()
        self._clients: List[socket.socket] = []
        self._clients_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"tcp-source:{self.port}")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._clients_lock:
                if self._stop.is_set():
                    # raced with disconnect(): its close loop already ran
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                self._clients.append(conn)
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def _reader(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                payload = _read_frame(conn)
                if payload is None:
                    return
                self.deliver(payload)
        except (OSError, ValueError) as exc:
            if not self._stop.is_set():
                # a malformed frame severs this client: say so — silent
                # drops cost hours of cross-host debugging
                log.warning("tcp source :%s dropping client connection "
                            "after bad frame: %r", self.port, exc)
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._clients_lock:
                if conn in self._clients:
                    self._clients.remove(conn)

    def disconnect(self) -> None:
        self._stop.set()
        with self._clients_lock:
            clients = list(self._clients)
            self._clients.clear()
        for c in clients:
            try:
                c.close()
            except OSError:
                pass
        try:
            self._srv.close()
        except OSError:
            pass


class TCPSink(Sink):
    """Frames each published payload to host:port.  The dial is LAZY (first
    publish): eager dialing would make cross-host start order mandatory —
    a sender booting before its receiver must not crash app start.  Publish
    failures raise so SinkRuntime's error handling applies; reconnect
    happens on the next publish."""

    _lock: Optional[threading.Lock] = None

    def connect(self) -> None:
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    def _ensure(self) -> socket.socket:
        if self._sock is None:
            host = self.options.get("host", "127.0.0.1")
            port = int(self.options["port"])
            self._sock = socket.create_connection((host, port), timeout=5.0)
        return self._sock

    def publish(self, payload: Any) -> None:
        with self._lock:
            try:
                try:
                    _send_frame(self._ensure(), payload)
                except OSError:
                    # drop the broken connection; retry once on a fresh one
                    self._drop()
                    _send_frame(self._ensure(), payload)
            except OSError as exc:
                # typed transport failure: SinkConnection's on.error
                # policy machinery keys on ConnectionUnavailableError
                self._drop()
                raise ConnectionUnavailableError(
                    f"tcp sink to "
                    f"{self.options.get('host', '127.0.0.1')}:"
                    f"{self.options.get('port')} unreachable: "
                    f"{exc!r}") from exc

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def disconnect(self) -> None:
        if self._lock is None:     # connect() never ran
            return
        with self._lock:
            self._drop()


register_source_type("tcp", TCPSource)
register_sink_type("tcp", TCPSink)
