"""Source/sink mappers (reference: CORE/stream/input/source/SourceMapper.java:39,
CORE/stream/output/sink/SinkMapper.java:44 and the passThrough mapper pair in
core; json/text/keyvalue mirror the official extension mappers' observable
behavior).

A SourceMapper turns a transport payload into attribute rows; a SinkMapper
turns output events into payloads.  `@map(type='...')` selects one;
`@attributes(...)` remaps source fields; `@payload(...)` templates sink
output.
"""
from __future__ import annotations

import json as _json
import re
from typing import Any, Dict, List, Optional

from ..core import event as ev
from ..exceptions import CompileError


class SourceMapper:
    def __init__(self, schema: ev.Schema, map_annotation):
        self.schema = schema
        self.ann = map_annotation
        # @attributes(a='path', b='path') or positional
        self.attribute_paths: Optional[List[str]] = None
        if map_annotation is not None:
            for sub in map_annotation.annotations:
                if sub.name.lower() == "attributes":
                    paths = []
                    for name in schema.names:
                        if name in sub.elements:
                            paths.append(sub.elements[name])
                        else:
                            paths.append(None)
                    pos = sub.positional_elements()
                    if pos:
                        paths = list(pos) + paths[len(pos):]
                    self.attribute_paths = paths

    def map(self, payload: Any, timestamp: int) -> List[ev.Event]:
        raise NotImplementedError


class PassThroughSourceMapper(SourceMapper):
    """payload is Event / data list / list of those (reference:
    PassThroughSourceMapper.java)."""

    def map(self, payload, timestamp):
        if isinstance(payload, ev.Event):
            return [payload]
        if isinstance(payload, (list, tuple)):
            if payload and isinstance(payload[0], (list, tuple, ev.Event)):
                return [p if isinstance(p, ev.Event)
                        else ev.Event(timestamp, list(p)) for p in payload]
            return [ev.Event(timestamp, list(payload))]
        raise ValueError(f"passThrough cannot map {type(payload).__name__}")


class JsonSourceMapper(SourceMapper):
    """JSON object / array / string payloads keyed by attribute name, with
    optional `$.path` expressions from @attributes (reference: the
    siddhi-map-json extension's default mapping)."""

    def _lookup(self, obj: Dict, path: str):
        cur = obj
        for part in path.lstrip("$.").split("."):
            if not isinstance(cur, dict) or part not in cur:
                return None
            cur = cur[part]
        return cur

    def _one(self, obj: Dict, timestamp: int) -> ev.Event:
        # optional {"event": {...}} envelope, as the reference emits
        if isinstance(obj, dict) and set(obj.keys()) == {"event"}:
            obj = obj["event"]
        data = []
        for i, name in enumerate(self.schema.names):
            if self.attribute_paths and self.attribute_paths[i]:
                data.append(self._lookup(obj, self.attribute_paths[i]))
            else:
                data.append(obj.get(name) if isinstance(obj, dict) else None)
        return ev.Event(timestamp, data)

    def map(self, payload, timestamp):
        if isinstance(payload, (str, bytes)):
            payload = _json.loads(payload)
        if isinstance(payload, list):
            return [self._one(o, timestamp) for o in payload]
        return [self._one(payload, timestamp)]


class KeyValueSourceMapper(SourceMapper):
    """dict payloads keyed by attribute name (reference: siddhi-map-keyvalue)."""

    def map(self, payload, timestamp):
        if not isinstance(payload, dict):
            raise ValueError("keyvalue mapper needs dict payloads")
        data = []
        for i, name in enumerate(self.schema.names):
            key = (self.attribute_paths[i]
                   if self.attribute_paths and self.attribute_paths[i]
                   else name)
            data.append(payload.get(key))
        return [ev.Event(timestamp, data)]


class TextSourceMapper(SourceMapper):
    """`attr:value` line format (reference: siddhi-map-text default:
    `a:"v",\nb:2`)."""

    _LINE = re.compile(r"\s*(\w+)\s*:\s*(.+?)\s*,?\s*$")

    def map(self, payload, timestamp):
        if isinstance(payload, bytes):
            payload = payload.decode()
        fields = {}
        for line in str(payload).splitlines():
            m = self._LINE.match(line)
            if m:
                v = m.group(2).strip()
                if v.startswith('"') and v.endswith('"'):
                    v = v[1:-1]
                fields[m.group(1)] = v
        data = []
        for name, t in zip(self.schema.names, self.schema.types):
            v = fields.get(name)
            if v is not None and t in ("INT", "LONG"):
                v = int(v)
            elif v is not None and t in ("FLOAT", "DOUBLE"):
                v = float(v)
            elif v is not None and t == "BOOL":
                v = v.lower() == "true"
            data.append(v)
        return [ev.Event(timestamp, data)]


# ---------------------------------------------------------------------------


class NoSuchAttributeError(CompileError):
    """@payload names an attribute the stream does not define
    (reference: NoSuchAttributeException from TemplateBuilder.parse)."""


class TemplateBuilder:
    """Sink payload template (reference behavior:
    CORE/util/transport/TemplateBuilder.java:39-150):

    - a template that IS exactly one attribute name emits the raw TYPED
      value ("object message"), not a string;
    - a backtick-wrapped no-whitespace template has the backticks stripped
      (lets a template that collides with an attribute name stay textual);
    - {{attr}} segments resolve by position, mixed freely with static
      text; an unknown attribute fails at CREATION time, not per event."""

    _DYN = re.compile(r"\{\{([^{}]*)\}\}")

    def __init__(self, schema: ev.Schema, template: str):
        t = str(template)
        self.obj_pos: Optional[int] = None
        stripped = t.strip()
        if stripped in schema.names:
            self.obj_pos = list(schema.names).index(stripped)
            self.parts: List = []
            return
        if re.match(r"^`[^\s]*`$", stripped):
            t = stripped[1:-1]
        names = list(schema.names)
        parts: List = []          # str literals and int positions
        last = 0
        for m in self._DYN.finditer(t):
            if m.start() > last:
                parts.append(t[last:m.start()])
            name = m.group(1)
            if name not in names:
                raise NoSuchAttributeError(
                    f"@payload attribute {name!r} does not exist in "
                    f"stream ({', '.join(names)})")
            parts.append(names.index(name))
            last = m.end()
        if last < len(t):
            parts.append(t[last:])
        self.parts = parts

    def build(self, e: ev.Event):
        if self.obj_pos is not None:
            return e.data[self.obj_pos]
        return "".join(p if isinstance(p, str) else str(e.data[p])
                       for p in self.parts)


class SinkMapper:
    def __init__(self, schema: ev.Schema, map_annotation):
        self.schema = schema
        self.ann = map_annotation
        self.payload_template: Optional[TemplateBuilder] = None
        if map_annotation is not None:
            for sub in map_annotation.annotations:
                if sub.name.lower() == "payload":
                    vals = list(sub.elements.values())
                    if vals:
                        self.payload_template = TemplateBuilder(
                            schema, str(vals[0]))

    def map(self, events: List[ev.Event]) -> List[Any]:
        raise NotImplementedError

    def _fill(self, template: "TemplateBuilder", e: ev.Event):
        return template.build(e)


class PassThroughSinkMapper(SinkMapper):
    def map(self, events):
        return list(events)


class JsonSinkMapper(SinkMapper):
    def map(self, events):
        outs = []
        for e in events:
            if self.payload_template:
                outs.append(self._fill(self.payload_template, e))
            else:
                outs.append(_json.dumps({"event": dict(
                    zip(self.schema.names, e.data))}))
        return outs


class KeyValueSinkMapper(SinkMapper):
    def map(self, events):
        return [dict(zip(self.schema.names, e.data)) for e in events]


class TextSinkMapper(SinkMapper):
    def map(self, events):
        outs = []
        for e in events:
            if self.payload_template:
                outs.append(self._fill(self.payload_template, e))
            else:
                outs.append(",\n".join(
                    f'{n}:"{v}"' if isinstance(v, str) else f"{n}:{v}"
                    for n, v in zip(self.schema.names, e.data)))
        return outs


SOURCE_MAPPERS = {
    "passThrough": PassThroughSourceMapper,
    "json": JsonSourceMapper,
    "keyvalue": KeyValueSourceMapper,
    "text": TextSourceMapper,
}

SINK_MAPPERS = {
    "passThrough": PassThroughSinkMapper,
    "json": JsonSinkMapper,
    "keyvalue": KeyValueSinkMapper,
    "text": TextSinkMapper,
}
