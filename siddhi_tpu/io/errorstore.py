"""Error store: failed events captured for inspection and replay.

Reference (what): the reference's `ErrorStore` SPI
(core.util.error.handler) captures events whose processing or publish
failed — `@OnError(action='STORE')` and `@sink(on.error='store')` both
feed it — and an admin API lists and replays them through the normal
input path.

TPU design (how): an in-memory, bounded, SPI-extensible store.  Entries
keep decoded host events (never device buffers), so storing is cheap
relative to the failure that produced it and replay re-enters through
`InputHandler.send` exactly like live traffic.  Capacity is bounded
with an explicit drop counter — an outage that overflows the store
must surface as a number, not an OOM.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional


def _py(v: Any) -> Any:
    """JSON-safe host value (numpy scalars -> python)."""
    return v.item() if hasattr(v, "item") else v


class ErroredEvent:
    """One failure capture: the events of one failed publish/processing
    attempt plus the error that rejected them."""

    __slots__ = ("id", "stream_id", "origin", "error", "ts_ms", "events")

    def __init__(self, id: int, stream_id: str, origin: str, error: str,
                 ts_ms: int, events: List):
        self.id = id
        self.stream_id = stream_id
        self.origin = origin          # 'sink' | 'junction'
        self.error = error
        self.ts_ms = ts_ms
        self.events = events          # List[core.event.Event]

    def to_dict(self) -> Dict:
        return {
            "id": self.id,
            "stream": self.stream_id,
            "origin": self.origin,
            "error": self.error,
            "ts_ms": self.ts_ms,
            "events": [
                {"timestamp": _py(e.timestamp),
                 "data": [_py(v) for v in e.data]}
                for e in self.events],
        }


class ErrorStore:
    """SPI: capture failed events, list them, hand them out for replay.
    Subclass to persist elsewhere (DB, queue); register per runtime via
    `runtime.error_store = MyStore(...)` before start()."""

    def store(self, stream_id: str, events: List, error: Exception,
              origin: str = "sink") -> None:
        raise NotImplementedError

    def entries(self, stream_id: Optional[str] = None) -> List[ErroredEvent]:
        raise NotImplementedError

    def take(self, ids: Optional[List[int]] = None,
             stream_id: Optional[str] = None) -> List[ErroredEvent]:
        """Remove and return matching entries (replay's exactly-once
        handoff: entries leave the store BEFORE re-injection)."""
        raise NotImplementedError

    def stats(self) -> Dict[str, int]:
        raise NotImplementedError


class InMemoryErrorStore(ErrorStore):
    """Bounded FIFO store.  At capacity the OLDEST entry is evicted
    (and counted) — under a sustained outage the operator replays the
    tail of the failure window, which is the actionable part."""

    def __init__(self, capacity: int = 1024):
        self.capacity = max(1, int(capacity))
        self._entries: List[ErroredEvent] = []
        self._lock = threading.Lock()
        self._next_id = 1
        self.dropped_total = 0
        self.stored_total = 0
        self.replayed_total = 0

    def store(self, stream_id, events, error, origin="sink"):
        if not events:
            return
        with self._lock:
            e = ErroredEvent(self._next_id, stream_id, origin, repr(error),
                             int(time.time() * 1000), list(events))
            self._next_id += 1
            self._entries.append(e)
            self.stored_total += len(events)
            while len(self._entries) > self.capacity:
                evicted = self._entries.pop(0)
                self.dropped_total += len(evicted.events)

    def entries(self, stream_id=None):
        with self._lock:
            return [e for e in self._entries
                    if stream_id is None or e.stream_id == stream_id]

    def take(self, ids=None, stream_id=None):
        with self._lock:
            want = set(ids) if ids is not None else None
            taken, kept = [], []
            for e in self._entries:
                match = (want is None or e.id in want) and \
                    (stream_id is None or e.stream_id == stream_id)
                (taken if match else kept).append(e)
            self._entries = kept
            self.replayed_total += sum(len(e.events) for e in taken)
            return taken

    def stats(self):
        with self._lock:
            return {
                "buffered": sum(len(e.events) for e in self._entries),
                "entries": len(self._entries),
                "capacity": self.capacity,
                "stored": self.stored_total,
                "dropped": self.dropped_total,
                "replayed": self.replayed_total,
            }
