"""Built-in lint rules: TPU hazards detectable before an app ever runs.

Every rule is grounded in a runtime hazard this engine actually has —
the rationale strings name the mechanism.  Severity policy: ERROR is
reserved for "this will break or silently lose data as written"; WARN
for "this degrades or explodes under production traffic"; INFO for
"you should know, but it may be intentional".  A clean production app
should lint with zero ERRORs; the shipped samples do.

Rule IDs are stable API: dashboards, CI configs, and severity overrides
key on them.  Never renumber — retire IDs instead.
"""
from __future__ import annotations

from typing import Iterator, List, Optional

from ..query_api.query import (
    EveryStateElement,
    InsertIntoStream,
    Partition,
    Query,
    ReturnStream,
    ValuePartitionType,
)
from .facts import (
    _BATCH_CAPACITY,
    AnalysisContext,
    iter_named_queries,
    pattern_atoms,
    query_kind,
)
from .findings import Finding
from .registry import rule


def _f(message: str, query: Optional[str] = None, node=None,
       hint: Optional[str] = None) -> Finding:
    """Finding skeleton — the driver stamps rule id / severity /
    source; `node` contributes its parser position when it has one."""
    return Finding(rule_id="", severity="", message=message, query=query,
                   pos=getattr(node, "pos", None), hint=hint)


def _mb(n: int) -> str:
    return f"{n / (1024 * 1024):.1f} MiB"


# ---------------------------------------------------------------------------
# state growth
# ---------------------------------------------------------------------------

@rule("STATE001", "WARN",
      "unbounded pattern state (`every` without `within`)",
      "An `every`-repeated pattern with no `within` bound keeps every "
      "pending partial match alive forever; the NFA slot block fills and "
      "new matches evict old ones unpredictably under sustained traffic.",
      "add `within <time>` to the pattern so stale partial matches "
      "expire")
def _every_without_within(ctx: AnalysisContext) -> Iterator[Finding]:
    for f in ctx.queries:
        if f.kind != "pattern":
            continue
        ist = f.query.input_stream
        if getattr(ist, "within_time", None) is not None:
            continue
        every = None

        def find_every(el):
            nonlocal every
            if isinstance(el, EveryStateElement) and every is None:
                every = el
            for attr in ("state_element", "next_state_element",
                         "stream_state_element",
                         "stream_state_element_1",
                         "stream_state_element_2"):
                sub = getattr(el, attr, None)
                if sub is not None:
                    find_every(sub)

        find_every(ist.state_element)
        if every is not None:
            yield _f("`every` pattern has no `within` bound — pending "
                     "match state accumulates without expiry "
                     f"({f.nfa_slots} NFA slots/key, eviction under "
                     "overflow)", query=f.name,
                     node=every if getattr(every, "pos", None)
                     else f.query)


@rule("STATE002", "INFO",
      "pattern emission block is effectively uncapped",
      "Non-partitioned patterns default to the 1<<30 'uncapped' "
      "compact_rows sentinel: the device emission block is sized by "
      "worst-case match fan-out, so a pathological batch can emit an "
      "arbitrarily large block in one dispatch.",
      "set `@emit(rows='N')` to bound the per-dispatch emission block")
def _uncapped_pattern_emission(ctx: AnalysisContext) -> Iterator[Finding]:
    for f in ctx.queries:
        if f.kind == "pattern" and f.emission_cap is None and \
                not f.emission_cap_explicit:
            yield _f("pattern emission cap is the uncapped sentinel — "
                     "worst-case match fan-out sizes the emission block",
                     query=f.name, node=f.query)


@rule("MEM001", "WARN",
      "query state exceeds the device-memory budget",
      "Window buffers, keyed-window slabs, and NFA slot blocks are "
      "dense device arrays sized at plan time (shape × dtype); a few "
      "oversized queries exhaust HBM before the first event arrives.",
      "shrink the window / `@capacity(keys=…, slots=…, window=…)`, or "
      "raise the lint budget if the deployment really has the HBM")
def _state_over_budget(ctx: AnalysisContext) -> Iterator[Finding]:
    from ..core.plan_facts import format_component_bytes
    budget = getattr(ctx.config, "state_budget_bytes",
                     128 * 1024 * 1024)
    for f in ctx.queries:
        if f.state_bytes is not None and f.state_bytes > budget:
            # same breakdown string the admission deploy gate prints in
            # its AdmissionDeniedError (core/plan_facts estimator)
            detail = f" ({format_component_bytes(f.state_components)})" \
                if f.state_components else ""
            yield _f(f"{f.state_bytes_origin} device state "
                     f"{_mb(f.state_bytes)} exceeds the "
                     f"{_mb(budget)} budget{detail}", query=f.name,
                     node=f.query)
    # merge-group shared buffers live under `merged:<group>` owners
    # (counted once, never per member) — grade them against the same
    # budget so sharing can't hide an oversized window from MEM001
    try:
        if ctx.runtime is not None:
            from ..observability.memory import component_bytes
            owners = component_bytes(ctx.runtime)
            origin = "measured"
        else:
            from ..core.plan_facts import static_state_components
            owners = static_state_components(ctx.app)
            origin = "estimated"
    except Exception:  # noqa: BLE001 — accounting must not kill lint
        owners = {}
        origin = "estimated"
    for owner in sorted(owners):
        if not owner.startswith("merged:"):
            continue
        comps = owners[owner]
        total = sum(comps.values())
        if total > budget:
            yield _f(f"{origin} shared device state {_mb(total)} of "
                     f"merge group {owner[len('merged:'):]!r} exceeds "
                     f"the {_mb(budget)} budget "
                     f"({format_component_bytes(comps)})")


# ---------------------------------------------------------------------------
# fusion / dispatch
# ---------------------------------------------------------------------------

@rule("FUSE001", "WARN",
      "@fuse requested but the wiring will exclude it",
      "A @fuse(batches=K) on a timer-bearing, keyed, sharded, or "
      "partitioned query is silently ignored at wiring time — the "
      "operator expects K× dispatch amortization and gets none.  The "
      "runtime only logs the exclusion at deploy; lint surfaces it "
      "before.",
      "remove the @fuse annotation, or restructure the query onto a "
      "fusable path")
def _fuse_excluded(ctx: AnalysisContext) -> Iterator[Finding]:
    for f in ctx.queries:
        if f.fuse_requested and f.fusion_exclusion:
            yield _f(f"@fuse(batches={f.fuse_requested}) will be "
                     f"ignored: {f.fusion_exclusion}", query=f.name,
                     node=f.query)


# ---------------------------------------------------------------------------
# emission caps
# ---------------------------------------------------------------------------

@rule("JOIN001", "WARN",
      "explicit join emission cap can overflow under worst-case "
      "cross-product",
      "An explicit @emit(rows='N') on a join warns-and-drops on "
      "overflow instead of growing; a batch joining against a full "
      "window can produce batch×window rows, silently truncated to N.",
      "raise @emit(rows=…) to cover batch_capacity × window rows, or "
      "drop the annotation and let the cap grow adaptively")
def _join_cap_overflow(ctx: AnalysisContext) -> Iterator[Finding]:
    for f in ctx.queries:
        if f.kind != "join" or not f.emission_cap_explicit or \
                f.emission_cap is None or f.join_side_rows is None:
            continue
        left, right = f.join_side_rows
        worst = _BATCH_CAPACITY * max(left, right)
        if f.emission_cap < worst:
            yield _f(f"explicit emission cap {f.emission_cap} rows < "
                     f"worst-case cross-product {worst} rows "
                     f"({_BATCH_CAPACITY}-row batch × "
                     f"{max(left, right)}-row window); overflow rows "
                     "are dropped", query=f.name, node=f.query)


@rule("JOIN002", "INFO",
      "equi-join fast path: ACTIVE (INFO) or inapplicable (WARN)",
      "The join ON-condition has a top-level equality conjunct.  When "
      "the equi-join fast path applies (both sides plain stream "
      "windows -> device key bucketing; or an indexed table side with "
      "a windowless trigger -> host hash probe) the plan evaluates "
      "only same-key candidate pairs and this rule reports INFO with "
      "the key attributes.  When the conjunct exists but the fast path "
      "cannot apply, the plan still evaluates the full [rows × rows] "
      "grid every batch — bytes-accessed scales with the grid, not the "
      "matches — and this rule WARNs with the wiring's exact reason "
      "(core/plan_facts.join_fastpath).",
      "bucket mode needs plain stream windows with no side [filter]; "
      "table mode needs an @Index/@PrimaryKey on the join key and a "
      "windowless trigger side; shrink the windows if the grid cost "
      "hurts")
def _equi_join_grid(ctx: AnalysisContext) -> Iterator[Finding]:
    from ..core.plan_facts import join_fastpath, table_probe_attrs_of
    app = ctx.app

    def side_kind(sid: str) -> str:
        if sid in app.aggregation_definition_map:
            return "aggregation"
        if sid in app.window_definition_map:
            return "named_window"
        if sid in app.table_definition_map:
            return "table"
        return "stream"

    def probe_attrs(sid: str):
        d = app.table_definition_map.get(sid)
        return table_probe_attrs_of(d) if d is not None else []

    for f in ctx.queries:
        if f.kind != "join":
            continue
        try:
            mode, pairs, reason = join_fastpath(
                f.query.input_stream, side_kind, probe_attrs)
        except Exception:  # noqa: BLE001 — analysis must not kill lint
            continue
        if not pairs:
            continue
        keys = ", ".join(
            f"{lv.stream_id}.{lv.attribute_name} == "
            f"{rv.stream_id}.{rv.attribute_name}"
            for _c, lv, rv in pairs)
        node = pairs[0][0] if getattr(pairs[0][0], "pos", None) \
            else f.query
        if mode is not None:
            fd = _f(f"equi-join fast path ACTIVE ({mode}): only "
                    f"same-key candidates are probed for {keys}",
                    query=f.name, node=node,
                    hint="no action needed")
            fd.severity = "INFO"
        else:
            fd = _f(f"ON-condition equality {keys} found but the fast "
                    f"path cannot apply: {reason} — the full "
                    "[rows × rows] grid is evaluated every batch",
                    query=f.name, node=node)
            fd.severity = "WARN"
        yield fd


# ---------------------------------------------------------------------------
# dataflow
# ---------------------------------------------------------------------------

def _stream_reads(app) -> set:
    reads = set()
    for _, q, _part in iter_named_queries(app):
        kind = query_kind(q)
        if kind == "plain":
            reads.add(q.input_stream.stream_id)
        elif kind == "join":
            reads.add(q.input_stream.left_input_stream.stream_id)
            reads.add(q.input_stream.right_input_stream.stream_id)
        else:
            for a in pattern_atoms(q.input_stream.state_element):
                reads.add(a.basic_single_input_stream.stream_id)
    for agg in app.aggregation_definition_map.values():
        sis = agg.basic_single_input_stream
        if sis is not None:
            reads.add(sis.stream_id)
    return reads


def _stream_writes(app) -> set:
    writes = set(app.trigger_definition_map)
    for _, q, _part in iter_named_queries(app):
        out = q.output_stream
        if out is not None and out.target_id:
            writes.add(out.target_id)
    return writes


@rule("DEAD001", "WARN",
      "stream defined but never referenced",
      "A stream no query reads and nothing writes is dead weight: its "
      "junction is wired, its schema interned, and a misspelled stream "
      "name elsewhere usually hides behind it.",
      "delete the definition, or fix the query that should be using it")
def _dead_stream(ctx: AnalysisContext) -> Iterator[Finding]:
    app = ctx.app
    reads = _stream_reads(app)
    writes = _stream_writes(app)
    for sid, sdef in app.stream_definition_map.items():
        if sid.startswith(("!", "#")) or sid in app.trigger_definition_map:
            continue
        if sdef.get_annotation("source") is not None or \
                sdef.get_annotation("sink") is not None:
            continue
        if sid not in reads and sid not in writes:
            yield _f(f"stream {sid!r} is never read or written by any "
                     "query, trigger, source, or sink", query=None,
                     node=sdef)


@rule("DEAD002", "INFO",
      "query output feeds nothing visible statically",
      "The query inserts into a stream that no downstream query reads "
      "and no @sink consumes.  Runtime callbacks may consume it — but "
      "if none is attached, every device step and emission fetch for "
      "this query is wasted work.",
      "add a downstream query or @sink, attach a runtime callback, or "
      "remove the query")
def _dead_output(ctx: AnalysisContext) -> Iterator[Finding]:
    app = ctx.app
    reads = _stream_reads(app)
    for f in ctx.queries:
        out = f.query.output_stream
        if not isinstance(out, InsertIntoStream) or \
                isinstance(out, ReturnStream):
            continue
        tgt = out.target_id
        if not tgt or tgt in app.table_definition_map or \
                tgt in app.window_definition_map:
            continue                 # tables/windows are stateful sinks
        sdef = app.stream_definition_map.get(tgt)
        if sdef is not None and sdef.get_annotation("sink") is not None:
            continue
        if tgt not in reads:
            yield _f(f"output stream {tgt!r} has no downstream query or "
                     "@sink (a runtime callback may still consume it)",
                     query=f.name, node=out)


# ---------------------------------------------------------------------------
# partitioning
# ---------------------------------------------------------------------------

@rule("PART001", "WARN",
      "partition key has unbounded cardinality",
      "Partition keys map to a finite device key slab (default 4096 "
      "slots).  A continuous-valued (float/double) key makes nearly "
      "every event a new key: the slab exhausts, purge churn replaces "
      "useful state, and per-key isolation degrades to noise.",
      "partition by a bounded-cardinality attribute (id, symbol, "
      "category), or bucket the value upstream")
def _float_partition_key(ctx: AnalysisContext) -> Iterator[Finding]:
    from ..query_api.expression import Variable
    for element in ctx.app.execution_element_list:
        if not isinstance(element, Partition):
            continue
        for sid, pt in element.partition_type_map.items():
            if not isinstance(pt, ValuePartitionType) or \
                    not isinstance(pt.expression, Variable):
                continue
            sdef = ctx.app.stream_definition_map.get(sid)
            if sdef is None:
                continue
            try:
                atype = sdef.attribute_type(
                    pt.expression.attribute_name)
            except KeyError:
                continue
            if atype in ("FLOAT", "DOUBLE"):
                yield _f(f"partition key {sid}.{pt.expression.attribute_name} "
                         f"is {atype} — continuous values exhaust the "
                         "finite partition key slab", query=None,
                         node=element)


def _mesh_devices(ctx: AnalysisContext) -> int:
    """Deploy-target mesh size: the live runtime's mesh when analyzing a
    runtime, else LintConfig.mesh_devices (CLI --mesh-size), else 0 =
    unknown (PART002 stays silent — mesh size is a deploy property)."""
    rt = ctx.runtime
    if rt is not None:
        from ..sharding import shard_count
        n = shard_count(rt)
        if n > 1:
            return n
    return int(getattr(ctx.config, "mesh_devices", 0) or 0)


@rule("PART002", "WARN",
      "partition key capacity below the mesh size",
      "A mesh-sharded partition spreads key slots round-robin over the "
      "devices (sharding/router.py), so at most key-capacity shards can "
      "ever hold a key.  A capacity below the mesh size guarantees idle "
      "shards: their state slabs are allocated, their collectives run, "
      "and they never process a key — the deployment pays for devices "
      "that cannot do work.",
      "raise @capacity(keys='N') to at least the mesh size — ideally a "
      "large multiple of it so routing balances — or serve the app "
      "unsharded")
def _undersized_partition_keys(ctx: AnalysisContext) -> Iterator[Finding]:
    from .facts import capacity_annotation
    n = _mesh_devices(ctx)
    if n < 2:
        return
    for f in ctx.queries:
        if f.partition is None:
            continue
        # the CONFIGURED capacity (runtime rounds it up to a mesh
        # multiple, so the planned value can never show the hazard)
        keys = capacity_annotation(f.query, f.partition).get("keys")
        if keys is None:
            from .facts import _PARTITION_KEYS
            keys = _PARTITION_KEYS
        if keys < n:
            yield _f(
                f"partition key capacity {keys} is below the {n}-device "
                f"mesh — at least {n - keys} shard(s) are guaranteed "
                f"idle", query=f.name, node=f.partition)


# ---------------------------------------------------------------------------
# expressions
# ---------------------------------------------------------------------------

def _filter_compares(app, q: Query):
    """(Compare node, owning stream def) for every filter expression in
    the query's input chains, plus the selector's having clause."""
    from ..query_api.expression import Compare, walk
    from ..query_api.query import Filter

    def sources(iq):
        kind = query_kind(iq)
        if kind == "plain":
            yield iq.input_stream
        elif kind == "join":
            yield iq.input_stream.left_input_stream
            yield iq.input_stream.right_input_stream
        else:
            for a in pattern_atoms(iq.input_stream.state_element):
                yield a.basic_single_input_stream

    for sis in sources(q):
        sdef = app.stream_definition_map.get(sis.stream_id) or \
            app.window_definition_map.get(sis.stream_id) or \
            app.table_definition_map.get(sis.stream_id)
        for h in getattr(sis, "stream_handlers", ()):
            if isinstance(h, Filter):
                for node in walk(h.expression):
                    if isinstance(node, Compare):
                        yield node, sdef
    if q.selector is not None and q.selector.having_expression is not None:
        for node in walk(q.selector.having_expression):
            from ..query_api.expression import Compare as _C
            if isinstance(node, _C):
                yield node, None


@rule("TYPE001", "WARN",
      "lossy type coercion in filter comparison",
      "Comparing a LONG attribute against a float/double literal "
      "coerces i64 to floating point on device; LONG values above 2^53 "
      "(and above 2^24 where DOUBLE lowers to f32 on TPU) compare "
      "wrongly — timestamps and ids are exactly the values that hit "
      "this.",
      "use an integer literal, or cast/scale the attribute explicitly")
def _lossy_filter_compare(ctx: AnalysisContext) -> Iterator[Finding]:
    from ..query_api.expression import Constant, Variable

    def attr_type(var, sdef):
        for d in ((ctx.app.stream_definition_map.get(var.stream_id),)
                  if var.stream_id else (sdef,)):
            if d is None:
                continue
            try:
                return d.attribute_type(var.attribute_name)
            except (KeyError, AttributeError):
                continue
        # pattern event refs (e1.price) resolve against the handler's
        # own stream definition
        if var.stream_id and sdef is not None:
            try:
                return sdef.attribute_type(var.attribute_name)
            except (KeyError, AttributeError):
                pass
        return None

    for f in ctx.queries:
        for cmp_node, sdef in _filter_compares(ctx.app, f.query):
            for a, b in ((cmp_node.left, cmp_node.right),
                         (cmp_node.right, cmp_node.left)):
                if isinstance(a, Variable) and isinstance(b, Constant) \
                        and b.type in ("FLOAT", "DOUBLE") and \
                        attr_type(a, sdef) == "LONG":
                    from ..observability.explain import render_expr
                    yield _f("LONG attribute "
                             f"{a.attribute_name!r} compared against "
                             f"{b.type} literal {b.value!r} — i64→float "
                             "coercion loses precision "
                             f"({render_expr(cmp_node)})", query=f.name,
                             node=cmp_node if getattr(cmp_node, "pos",
                                                      None)
                             else f.query)
                    break


@rule("NULL001", "WARN",
      "nullable attribute hits the in-band null encoding's divergences",
      "Nulls are in-band reserved values on device (INT/LONG use the "
      "dtype minimum, BOOL has no spare value — PARITY.md).  When the "
      "null-flow pass proves an attribute can be null (outer-join "
      "unmatched side, optional pattern atom, empty-set aggregation) "
      "and it flows into a compare or arithmetic, semantics diverge "
      "from the reference: a legitimate INT_MIN/LONG_MIN value is "
      "treated as null, and a null BOOL compares as False instead of "
      "making the comparison false.  This is the static half of "
      "ROADMAP item 5 (validity bit-planes delete the divergence).",
      "guard with `is null` / coalesce() before comparing, use a "
      "FLOAT/DOUBLE column (NaN null is out-of-band for comparisons), "
      "or accept the documented INT_MIN-as-value semantics")
def _nullable_sentinel_flow(ctx: AnalysisContext) -> Iterator[Finding]:
    from ..query_api import expression as ex
    from .typeflow import SENTINEL_DIVERGENT, infer_app
    try:
        flow = infer_app(ctx.app)
    except Exception:  # noqa: BLE001 — inference must not kill lint
        return
    for f in ctx.queries:
        qf = flow.queries.get(f.name)
        if qf is None:
            continue
        seen = set()
        for use in qf.uses:
            if not isinstance(use.node, (ex.Compare, ex.Add,
                                         ex.Subtract, ex.Multiply,
                                         ex.Divide, ex.Mod)):
                continue
            if use.context == "on":
                continue      # join ON null-keys simply never match
            for side, info in zip((use.node.left, use.node.right),
                                  use.operands):
                if not info.nullable or \
                        info.type not in SENTINEL_DIVERGENT:
                    continue
                if id(use.node) in seen:
                    break
                seen.add(id(use.node))
                what = side.attribute_name \
                    if isinstance(side, ex.Variable) else "expression"
                op = "compared" if isinstance(use.node, ex.Compare) \
                    else "used in arithmetic"
                divergence = (
                    "null decodes as False, so `== false` matches "
                    "nulls" if info.type == "BOOL" else
                    f"a legitimate {info.type}_MIN value is treated "
                    "as null")
                yield _f(
                    f"nullable {info.type} {what!r} "
                    f"({info.why or 'null-flow'}) is {op} — "
                    f"{divergence}; reference semantics diverge "
                    "(PARITY.md in-band nulls)", query=f.name,
                    node=use.node if getattr(use.node, "pos", None)
                    else f.query)
                break


# ---------------------------------------------------------------------------
# rate limiting
# ---------------------------------------------------------------------------

@rule("RATE001", "WARN",
      "rate limit interacts with batch emission to drop events",
      "The rate limiter samples the emission stream AFTER device "
      "compaction and batch stacking: an explicit @emit cap truncates "
      "rows before first/last selection sees them, and under @fuse the "
      "limiter's clock only advances at dispatch — up to K-1 batches "
      "late for time/snapshot limiters.",
      "drop the explicit @emit cap, or un-fuse the query, or accept "
      "the documented loss semantics")
def _ratelimit_batch_interaction(ctx: AnalysisContext
                                 ) -> Iterator[Finding]:
    for f in ctx.queries:
        rate = f.query.output_rate
        if rate is None:
            continue
        if f.emission_cap_explicit and f.emission_cap is not None:
            yield _f(f"explicit @emit(rows={f.emission_cap}) drops "
                     "overflow rows before the "
                     f"`output {rate.behavior.lower()} every …` limiter "
                     "samples them", query=f.name, node=rate)
        elif f.fuse_requested and rate.type in ("TIME", "SNAPSHOT"):
            yield _f(f"@fuse(batches={f.fuse_requested}) delays "
                     "emission up to "
                     f"{f.fuse_requested - 1} batches behind the "
                     f"{rate.type.lower()}-based rate limiter's clock",
                     query=f.name, node=rate)


# ---------------------------------------------------------------------------
# deployment hygiene
# ---------------------------------------------------------------------------

@rule("APP001", "INFO",
      "app has no @app:name",
      "The REST service keys deployments by app name and rejects "
      "duplicates; every unnamed app collides on the default "
      "'SiddhiApp', so at most one can ever be deployed.",
      "add @app:name('…') at the top of the app")
def _unnamed_app(ctx: AnalysisContext) -> Iterator[Finding]:
    if not ctx.app.name:
        yield _f("app is unnamed — REST deployments collide on the "
                 "default name 'SiddhiApp'")


# ---------------------------------------------------------------------------
# I/O resilience
# ---------------------------------------------------------------------------

@rule("SINK001", "WARN",
      "@sink on a high-rate stream silently drops failed events",
      "The default @sink(on.error='log') policy logs a transport "
      "failure and DROPS the affected events.  On a stream fed at "
      "engine rate (a query output or an @async ingress) a short "
      "broker/socket outage silently loses a window of output with "
      "nothing but a log line to show for it — and no fault stream is "
      "defined to catch them either.",
      "set @sink(on.error='retry') (buffered redelivery), 'store' "
      "(error store + replay), 'wait' (backpressure), or 'stream' + a "
      "`!stream` consumer, or add @OnError(action='STREAM') to the "
      "stream")
def _sink_silent_drop(ctx: AnalysisContext) -> Iterator[Finding]:
    app = ctx.app
    writes = _stream_writes(app)
    for sid, sdef in app.stream_definition_map.items():
        if sid.startswith(("!", "#")):
            continue
        # high-rate: events arrive at engine rate (query output) or
        # through an async ingress ring, not hand-fed test traffic
        if sid not in writes and sdef.get_annotation("async") is None:
            continue
        on_err = sdef.get_annotation("OnError")
        if on_err is not None and \
                str(on_err.element("action", "LOG")).upper() == "STREAM":
            continue
        for ann in sdef.annotations:
            if ann.name.lower() != "sink":
                continue
            policy = str(ann.element("on.error", "log")).lower()
            if policy != "log":
                continue
            stype = ann.element("type") or ann.element(None)
            yield _f(f"@sink(type={str(stype)!r}) on high-rate stream "
                     f"{sid!r} uses the default on.error='log' and no "
                     "fault stream is defined — a transport outage "
                     "silently drops every event published during it",
                     query=None, node=ann)


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def _global_ceiling(ctx: AnalysisContext) -> int:
    """Deploy-target global state ceiling, bytes: the live manager's
    `admission.global.max.state.bytes` when analyzing a runtime, else
    LintConfig.global_state_ceiling_bytes (CLI --global-ceiling), else
    0 = unknown (the size half of ADM001 stays silent)."""
    rt = ctx.runtime
    if rt is not None:
        try:
            cm = getattr(getattr(rt, "manager", None),
                         "config_manager", None)
            v = cm.extract_property("admission.global.max.state.bytes") \
                if cm is not None else None
            if v:
                return int(float(v))
        except Exception:  # noqa: BLE001 — config must not break lint
            pass
    return int(getattr(ctx.config, "global_state_ceiling_bytes", 0) or 0)


def _overload_explicit(ctx: AnalysisContext) -> bool:
    """Did anyone CHOOSE an overload policy for this app?  Runtime:
    the controller's policy_explicit (annotation, manager property, or
    REST PUT).  Static: the @app:admission annotation alone."""
    rt = ctx.runtime
    if rt is not None:
        adm = getattr(rt, "admission", None)
        if adm is not None:
            return bool(getattr(adm, "policy_explicit", False))
    ann = ctx.app.get_annotation("app:admission")
    return ann is not None and ann.element("overload") is not None


@rule("ADM001", "WARN",
      "app will collide with the admission controller at deploy or "
      "under load",
      "Two deploy-time hazards the admission layer (core/admission.py) "
      "turns into runtime denials: an app whose static state estimate "
      "already exceeds the box's configured global memory ceiling will "
      "be REJECTED at deploy (`admission.global.max.state.bytes`), and "
      "an app fed at transport rate by a @source with no explicit "
      "`admission.overload` policy gets the default 'block' ladder — "
      "under overload its transport delivery thread backpressures to "
      "the deadline and then errors, which for a socket feed usually "
      "means disconnects, not throttling.",
      "shrink the state (window/@capacity) below the global ceiling, "
      "and declare @app:admission(overload='shed'|'degrade'|'block', "
      "max.events.per.sec='…') so overload behavior is chosen, not "
      "defaulted")
def _admission_hazards(ctx: AnalysisContext) -> Iterator[Finding]:
    ceiling = _global_ceiling(ctx)
    if ceiling > 0:
        total = sum(f.state_bytes or 0 for f in ctx.queries)
        if total > ceiling:
            worst = max((f for f in ctx.queries if f.state_bytes),
                        key=lambda f: f.state_bytes, default=None)
            yield _f(f"total {'measured' if ctx.runtime is not None else 'estimated'} "
                     f"device state {_mb(total)} exceeds the global "
                     f"admission ceiling {_mb(ceiling)} — deploy would "
                     "be denied on a box honoring it",
                     query=worst.name if worst is not None else None,
                     node=worst.query if worst is not None else None)
    # transport-rate ingest with a defaulted overload policy
    if _overload_explicit(ctx):
        return
    for sid, sdef in ctx.app.stream_definition_map.items():
        if sid.startswith(("!", "#")):
            continue
        for ann in sdef.annotations:
            if ann.name.lower() != "source":
                continue
            stype = str(ann.element("type") or ann.element(None) or "")
            if stype.lower() == "inmemory":
                continue      # hand-fed test transport, not a feed
            yield _f(f"@source(type={stype!r}) feeds {sid!r} at "
                     "transport rate but no admission.overload policy "
                     "is declared — overload backpressures the "
                     "delivery thread with the default 'block' ladder",
                     query=None, node=ann)


@rule("MQO001", "INFO",
      "multi-query merge: groups formed (and why queries stay out)",
      "N co-resident queries on one stream normally cost N device "
      "dispatches, N emission fetches, and N recompile owners per "
      "batch.  The whole-app optimizer (siddhi_tpu/optimizer) merges "
      "eligible queries into ONE jitted dispatch per group — and "
      "queries with identical pre-window chains + window specs + "
      "group-by layouts additionally share one window buffer.  This "
      "rule reports each group the planner will form and, for every "
      "query left out, the planner's exact ineligibility reason "
      "(core/plan_facts.merge_plan — the same single source the "
      "runtime pass and EXPLAIN's `merge` node read).",
      "align @async/@pipeline/@fuse/@serve decorations, window specs, "
      "and "
      "pre-window filters across co-resident queries to widen merge "
      "groups; set optimizer.merge.enabled=false to opt out")
def _merge_groups(ctx: AnalysisContext) -> Iterator[Finding]:
    from ..core.plan_facts import merge_plan
    # a single-query app has nothing to merge: stay silent instead of
    # explaining why one query is alone
    if len(ctx.queries) < 2:
        return
    rt = ctx.runtime
    if rt is not None and hasattr(rt, "merged_groups"):
        # live runtime: report what the pass ACTUALLY did (config may
        # have disabled it; dynamic demotions may have shrunk groups)
        by_name = {f.name: f for f in ctx.queries}
        for gid in sorted(rt.merged_groups):
            mg = rt.merged_groups[gid]
            shared = sum(1 for mode, _ in mg.units if mode == "shared")
            first = by_name.get(mg.members[0].name)
            yield _f(f"merge group {gid!r} compiles "
                     f"{len(mg.members)} queries into one dispatch "
                     f"({shared} shared window unit(s)): "
                     + ", ".join(m.name for m in mg.members),
                     query=first.name if first is not None else None,
                     node=first.query if first is not None else None,
                     hint="no action needed")
        for name in sorted(getattr(rt, "_merge_reasons", {})):
            f = by_name.get(name)
            yield _f(f"not merged: {rt._merge_reasons[name]}",
                     query=name,
                     node=f.query if f is not None else None)
        return
    try:
        plan = merge_plan(ctx.app,
                          mesh_devices=int(getattr(ctx.config,
                                                   "mesh_devices", 0)
                                           or 0))
    except Exception:  # noqa: BLE001 — analysis must not kill lint
        return
    by_name = {f.name: f for f in ctx.queries}
    for g in plan["groups"]:
        shared = sum(1 for u in g["units"] if u["mode"] == "shared")
        first = by_name.get(g["members"][0])
        yield _f(f"merge group {g['group']!r} compiles "
                 f"{len(g['members'])} queries into one dispatch "
                 f"({shared} shared window unit(s)): "
                 + ", ".join(g["members"]),
                 query=first.name if first is not None else None,
                 node=first.query if first is not None else None,
                 hint="no action needed")
    for name in sorted(plan["reasons"]):
        f = by_name.get(name)
        yield _f(f"not merged: {plan['reasons'][name]}", query=name,
                 node=f.query if f is not None else None)


@rule("SERVE001", "WARN",
      "@serve query drains into a synchronous-blocking sink",
      "Device-resident serving (siddhi_tpu/serving) moves delivery onto "
      "ONE shared drainer thread per app: the send path only appends to "
      "an on-device ring, and the drainer fetches and publishes later.  "
      "A sink with on.error='wait' blocks its publish call until the "
      "transport recovers — on the drainer thread that stall is "
      "head-of-line blocking for EVERY serving query's ring: occupancy "
      "climbs to high-water, producers fall back to bounded ring "
      "backpressure, and the app's serving path degrades to the "
      "synchronous behavior @serve was meant to remove.",
      "use @sink(on.error='retry'|'store'|'stream') on streams fed by "
      "@serve queries so the drainer never parks, or drop @serve from "
      "the query feeding the 'wait' sink")
def _serve_blocking_sink(ctx: AnalysisContext) -> Iterator[Finding]:
    from ..core.plan_facts import serve_enabled
    app = ctx.app
    rt = ctx.runtime
    for f in ctx.queries:
        q = f.query
        # serving? live runtime wins (serving.enabled config can turn
        # the app on wholesale); statically only annotations decide
        if rt is not None:
            qr = getattr(rt, "query_runtimes", {}).get(f.name)
            serving = bool(getattr(qr, "serve_emit", False))
        else:
            try:
                serving = bool(serve_enabled(app, q))
            except Exception:  # noqa: BLE001 — analysis must not die
                serving = False
        if not serving:
            continue
        out = q.output_stream
        tgt = getattr(out, "target_id", None)
        sdef = app.stream_definition_map.get(tgt) if tgt else None
        if sdef is None:
            continue
        for ann in sdef.annotations:
            if ann.name.lower() != "sink":
                continue
            if str(ann.element("on.error", "log")).lower() != "wait":
                continue
            stype = ann.element("type") or ann.element(None)
            yield _f(f"@serve query {f.name!r} feeds "
                     f"@sink(type={str(stype)!r}, on.error='wait') on "
                     f"{tgt!r} — a transport stall parks the shared "
                     "drainer thread and backpressures every serving "
                     "ring in the app", query=f.name, node=ann)


@rule("STATE003", "WARN",
      "sized state capacity far from observed high-water",
      "Every stateful structure here occupies FIXED device shapes sized "
      "at compile time: keyed window slabs, group-slot arenas, NFA key "
      "blocks, join key lanes.  The state observatory "
      "(observability/stateobs.py) tracks each structure's occupancy "
      "and high-water from its host mirror.  A capacity 4x or more "
      "above the observed high-water wastes HBM against admission's "
      "state ceilings for the whole app lifetime; an occupancy at 90%+ "
      "of a NON-growable cap means the next new key raises a slot-"
      "exhaustion error instead of degrading gracefully.",
      "resize via the cited config key (e.g. @capacity(keys='N')) to "
      "~2x the observed high-water; the high-water persists across "
      "restarts in snapshots, so a bench-scale soak gives a durable "
      "sizing hint")
def _state_capacity_mismatch(ctx: AnalysisContext) -> Iterator[Finding]:
    rt = ctx.runtime
    if rt is None:
        return          # utilization is measured, never guessed
    from ..observability.stateobs import (
        _NEAR_CAPACITY_EXEMPT, collect, near_capacity, obs_enabled)
    if not obs_enabled(rt):
        return
    try:
        collect(rt)
        snap = rt.stats.stateobs.snapshot()
    except Exception:  # noqa: BLE001 — analysis must not die
        return
    for q, structures in snap["structures"].items():
        for s, rec in structures.items():
            hwm, cap = rec["high_water"], rec["capacity"]
            if rec["growable"] or s in _NEAR_CAPACITY_EXEMPT:
                continue
            # oversized: enough traffic to trust the high-water, and
            # the configured cap dwarfs it
            if hwm >= 8 and cap >= 4 * hwm:
                ck = rec.get("config_key") or "its capacity annotation"
                yield _f(f"{s} capacity {cap} is {cap / hwm:.0f}x the "
                         f"observed high-water {hwm} — device state is "
                         "sized for traffic that never arrived",
                         query=q,
                         hint=f"shrink {ck} toward ~{max(16, 2 * hwm)} "
                              "(2x observed high-water)")
    for rec in near_capacity(rt, snap):
        ck = rec.get("config_key") or "its capacity annotation"
        yield _f(f"{rec['structure']} occupancy {rec['occupancy']}/"
                 f"{rec['capacity']} "
                 f"({rec['utilization'] * 100:.0f}%) on a non-growable "
                 "cap — the next new key past the cap raises instead "
                 "of degrading", query=rec["query"],
                 hint=f"raise {ck} before the arena exhausts")


ALL_RULE_IDS: List[str] = [
    "STATE001", "STATE002", "MEM001", "FUSE001", "JOIN001", "JOIN002",
    "DEAD001", "DEAD002", "NULL001", "PART001", "PART002", "TYPE001",
    "RATE001", "APP001", "SINK001", "ADM001", "MQO001", "SERVE001",
    "STATE003",
]
