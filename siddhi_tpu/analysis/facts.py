"""Per-query plan facts for the static analyzer.

Two builders produce the same `QueryFacts` shape:

- `facts_from_app(app)` — pure AST walk plus a *static mini-planner*
  that predicts the facts the real planner would compute (window
  processor class and its `needs_timer`, key/slot capacities, the
  emission-cap sentinel, shape×dtype state-byte estimates) without
  constructing a runtime or touching jax.  Fusion-exclusion reasons are
  NOT re-derived: a shim `planned` carrying the statically-known
  properties is fed through the real `core.fusion.ineligible_reason`,
  so lint reports the exact string the wiring would log at first
  dispatch.

- `facts_from_runtime(rt)` — reads the *actual* planned-query
  dataclasses of a live SiddhiAppRuntime: `describe()` plan facts,
  `core.plan_facts.fusion_exclusion`, and the metadata-only
  `observability.memory` accounting.  Attribute and shape/dtype reads
  only — analysis never executes, traces, or fetches (the lint guard
  test monkeypatches `jax.jit`/`jax.device_get` over a full run).

Query naming mirrors `SiddhiAppRuntime._query_name` exactly (`@info`
name, else `query<i>` numbered across top-level queries and partition
bodies), so findings join against explain/metrics/healthz by name.
"""
from __future__ import annotations

import dataclasses
import types
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..query_api.app import SiddhiApp
from ..query_api.definition import AbstractDefinition
from ..query_api.query import (
    AbsentStreamStateElement,
    CountStateElement,
    EveryStateElement,
    JoinInputStream,
    LogicalStateElement,
    NextStateElement,
    Partition,
    Query,
    RangePartitionType,
    StateInputStream,
    StreamStateElement,
    Window,
)

# mirrors of the planner/runtime defaults (planner.plan_single_query,
# runtime._add_query/_add_partition) — the static estimates must predict
# what those paths would build
_BATCH_CAPACITY = 512
_WINDOW_HINT = 2048
_PARTITION_WINDOW_HINT = 128
_PARTITION_KEYS = 4096
_NFA_SLOTS = 8
# columnar buffer overhead per row beyond the payload columns:
# ts i64 + seq i64 + gslot i32 + alive bool (core/window.py empty_buffer)
_ROW_OVERHEAD = 8 + 8 + 4 + 1


@dataclasses.dataclass
class QueryFacts:
    """What the analyzer knows about one query, from either builder."""

    name: str
    query: Query
    kind: str                           # plain | pattern | join
    origin: str = "static"              # static | planned
    partition: Optional[Partition] = None
    needs_timer: bool = False
    keyed_window: bool = False
    fuse_requested: int = 0
    fusion_exclusion: Optional[str] = None
    # rendered emission cap (None = uncapped / capacity-bounded)
    emission_cap: Optional[int] = None
    emission_cap_explicit: bool = False
    # per-query device state, bytes (shape×dtype arithmetic)
    state_bytes: Optional[int] = None
    state_bytes_origin: str = "estimated"   # estimated | measured
    key_capacity: int = 1
    nfa_slots: int = _NFA_SLOTS
    # join sides: (left rows, right rows) worst-case resident window rows
    join_side_rows: Optional[Tuple[int, int]] = None

    def pos(self) -> Optional[Tuple[int, int]]:
        return getattr(self.query, "pos", None)


@dataclasses.dataclass
class AnalysisContext:
    """Everything a rule may look at."""

    app: SiddhiApp
    queries: List[QueryFacts]
    config: Any = None                  # registry.LintConfig
    source_name: str = "<app>"
    runtime: Any = None                 # live SiddhiAppRuntime, or None


# ---------------------------------------------------------------------------
# shared AST helpers (used by facts builders AND rules)
# ---------------------------------------------------------------------------

def iter_named_queries(app: SiddhiApp):
    """(name, query, partition|None) with runtime-identical naming."""
    qi = 0

    def name_of(q: Query) -> str:
        info = q.get_annotation("info")
        if info:
            n = info.element("name")
            if n:
                return n
        return f"query{qi + 1}"

    for element in app.execution_element_list:
        if isinstance(element, Query):
            yield name_of(element), element, None
            qi += 1
        elif isinstance(element, Partition):
            for q in element.query_list:
                yield name_of(q), q, element
                qi += 1


def window_handler(sis) -> Optional[Window]:
    for h in getattr(sis, "stream_handlers", ()):
        if isinstance(h, Window):
            return h
    return None


def pattern_atoms(el):
    """Flat list of the stream/absent atoms of a state-element tree."""
    out = []

    def rec(e):
        if isinstance(e, (StreamStateElement, AbsentStreamStateElement)):
            out.append(e)
        elif isinstance(e, CountStateElement):
            rec(e.stream_state_element)
        elif isinstance(e, LogicalStateElement):
            rec(e.stream_state_element_1)
            rec(e.stream_state_element_2)
        elif isinstance(e, NextStateElement):
            rec(e.state_element)
            rec(e.next_state_element)
        elif isinstance(e, EveryStateElement):
            rec(e.state_element)

    rec(el)
    return out


def window_needs_timer(win: Optional[Window]) -> bool:
    """needs_timer of the processor class the planner would pick —
    resolved from the live WINDOW_TYPES registry, never re-listed here."""
    if win is None:
        return False
    from ..core.window import WINDOW_TYPES
    full = (win.namespace + ":" if win.namespace else "") + win.name
    cls = WINDOW_TYPES.get(full)
    return bool(getattr(cls, "needs_timer", False)) if cls else False


def _row_bytes(sdef: Optional[AbstractDefinition]) -> int:
    """Bytes per buffered window row: payload columns (device dtypes via
    event.dtype_of — STRING is an interned i32, DOUBLE an f32 on TPU)
    plus the fixed Buffer bookkeeping columns."""
    from ..core import event as ev
    n = _ROW_OVERHEAD
    for a in getattr(sdef, "attribute_list", ()):
        try:
            n += int(np.dtype(ev.dtype_of(a.type)).itemsize)
        except Exception:  # noqa: BLE001 — OBJECT columns etc.
            n += 8
    return n


def window_capacity(win: Optional[Window], hint: int) -> int:
    """Resident-row capacity the planner would give this window: the
    first non-time integer parameter (length/lengthBatch/sort/... row
    counts), else the capacity hint time-based windows are built with."""
    if win is None:
        return _BATCH_CAPACITY
    from ..query_api.expression import Constant
    for p in win.parameters:
        if isinstance(p, Constant) and p.type in ("INT", "LONG") and \
                not getattr(p, "is_time", False):
            return max(1, int(p.value))
    return hint


def capacity_annotation(q: Query, part: Optional[Partition]
                        ) -> Dict[str, int]:
    """@capacity(keys=, slots=, window=) merged across the query and its
    partition (runtime._add_partition scans both)."""
    out: Dict[str, int] = {}
    anns = list(q.annotations)
    if part is not None:
        anns += list(part.annotations)
        for pq in part.query_list:
            anns += list(pq.annotations)
    for ann in anns:
        if ann.name.lower() == "capacity":
            for k in ("keys", "slots", "window"):
                v = ann.element(k)
                if v is not None:
                    out[k] = int(v)
    return out


def fuse_requested(app: SiddhiApp, q: Query) -> int:
    """Static mirror of runtime._fuse_enabled: @fuse on the query, any
    input stream definition, or @app:fuse.  Returns K (0 = off)."""
    ann = q.get_annotation("fuse")
    if ann is None:
        ist = q.input_stream
        sids = getattr(ist, "all_stream_ids", None) or \
            [getattr(ist, "stream_id", None)]
        for sid in sids:
            sdef = app.stream_definition_map.get(sid)
            if sdef is not None and \
                    sdef.get_annotation("fuse") is not None:
                ann = sdef.get_annotation("fuse")
                break
    if ann is None:
        ann = app.get_annotation("app:fuse")
    if ann is None:
        return 0
    k = ann.element("batches", ann.element(None, 8)) or 8
    return max(1, int(k))


def emit_annotation_rows(q: Query) -> Optional[int]:
    ann = q.get_annotation("emit")
    if ann is None:
        return None
    v = ann.element("rows")
    return int(v) if v is not None else None


def query_kind(q: Query) -> str:
    if isinstance(q.input_stream, JoinInputStream):
        return "join"
    if isinstance(q.input_stream, StateInputStream):
        return "pattern"
    return "plain"


# ---------------------------------------------------------------------------
# static path
# ---------------------------------------------------------------------------

def _static_exclusion(app: SiddhiApp, q: Query, kind: str,
                      part: Optional[Partition],
                      needs_timer: bool, keyed: bool) -> Optional[str]:
    """Feed statically-known plan properties through the REAL
    core.fusion.ineligible_reason via a shim `planned`, so the string
    lint prints is the one the wiring would log.  Mesh sharding is a
    deploy-time property (unknowable from source), so the static path
    assumes unsharded — the runtime path reports the sharded reasons."""
    from ..core import fusion
    ist = q.input_stream
    present = object()      # stands in for "this step/body exists"
    if kind == "plain":
        range_part = part is not None and any(
            isinstance(pt, RangePartitionType)
            for pt in part.partition_type_map.values())
        planned = types.SimpleNamespace(
            needs_timer=needs_timer, keyed_window=keyed,
            partition_key_fn=present if range_part else None,
            raw_step=present)
    elif kind == "pattern":
        has_absent = any(
            isinstance(a, AbsentStreamStateElement)
            for a in pattern_atoms(ist.state_element))
        planned = types.SimpleNamespace(
            timer_step=present if has_absent else None,
            partition_positions={"_": [0]} if part is not None else None,
            mesh=None, step_bodies=present)
    else:
        planned = types.SimpleNamespace(
            needs_timer=needs_timer,
            step_left=present, raw_left=present,
            step_right=present, raw_right=present)
    try:
        return fusion.ineligible_reason(
            types.SimpleNamespace(planned=planned), kind)
    except Exception:  # noqa: BLE001 — a shim gap must not kill lint
        return None


def _static_state_bytes(app: SiddhiApp, q: Query, kind: str,
                        part: Optional[Partition], caps: Dict[str, int],
                        keys: int) -> Optional[int]:
    """Shape×dtype estimate of the device state the planner would
    allocate (windows and NFA slot blocks; group-by slabs are bounded
    and small by comparison)."""
    defs = app.stream_definition_map

    def stream_def(sid):
        return defs.get(sid) or app.window_definition_map.get(sid)

    hint = caps.get(
        "window",
        _PARTITION_WINDOW_HINT if part is not None else _WINDOW_HINT)
    if kind == "plain":
        win = window_handler(q.input_stream)
        if win is None:
            return None
        rows = window_capacity(win, hint)
        per_key = rows * _row_bytes(stream_def(q.input_stream.stream_id))
        return per_key * (keys if part is not None else 1)
    if kind == "join":
        total = 0
        for sis in (q.input_stream.left_input_stream,
                    q.input_stream.right_input_stream):
            win = window_handler(sis)
            if win is not None:
                total += window_capacity(win, _WINDOW_HINT) * \
                    _row_bytes(stream_def(sis.stream_id))
        return total or None
    # pattern: per-key NFA slot block — `slots` pending matches per key,
    # each capturing one row per pattern state
    atoms = pattern_atoms(q.input_stream.state_element)
    slots = caps.get("slots", _NFA_SLOTS)
    per_state = max(
        (_row_bytes(stream_def(a.basic_single_input_stream.stream_id))
         for a in atoms), default=_ROW_OVERHEAD)
    return (keys if part is not None else 1) * slots * \
        max(1, len(atoms)) * per_state


def facts_from_app(app: SiddhiApp) -> List[QueryFacts]:
    out: List[QueryFacts] = []
    for name, q, part in iter_named_queries(app):
        kind = query_kind(q)
        caps = capacity_annotation(q, part)
        keys = caps.get("keys", _PARTITION_KEYS)
        win = None
        if kind == "plain":
            win = window_handler(q.input_stream)
            needs_timer = window_needs_timer(win)
            session_keyed = win is not None and win.name == "session" \
                and len(win.parameters) >= 2
            keyed = session_keyed or (part is not None and win is not None)
        elif kind == "join":
            needs_timer = any(
                window_needs_timer(window_handler(s))
                for s in (q.input_stream.left_input_stream,
                          q.input_stream.right_input_stream))
            keyed = False
        else:
            needs_timer = any(
                isinstance(a, AbsentStreamStateElement)
                for a in pattern_atoms(q.input_stream.state_element))
            keyed = False

        from ..core.plan_facts import UNCAPPED_SENTINEL, render_cap
        emit_rows = emit_annotation_rows(q)
        cap = None
        explicit = emit_rows is not None
        if kind == "pattern":
            cap = render_cap(
                emit_rows if explicit
                else (8 if part is not None else UNCAPPED_SENTINEL))
        elif kind == "join":
            cap = render_cap(emit_rows) if explicit else None

        k = fuse_requested(app, q)
        f = QueryFacts(
            name=name, query=q, kind=kind, origin="static",
            partition=part, needs_timer=needs_timer, keyed_window=keyed,
            fuse_requested=k,
            fusion_exclusion=_static_exclusion(
                app, q, kind, part, needs_timer, keyed) if k else None,
            emission_cap=cap, emission_cap_explicit=explicit,
            state_bytes=_static_state_bytes(app, q, kind, part, caps,
                                            keys),
            state_bytes_origin="estimated",
            key_capacity=keys if (part is not None or keyed) else 1,
            nfa_slots=caps.get("slots", _NFA_SLOTS),
        )
        if kind == "join":
            defs = app.stream_definition_map
            sides = []
            for sis in (q.input_stream.left_input_stream,
                        q.input_stream.right_input_stream):
                w = window_handler(sis)
                sides.append(window_capacity(w, _WINDOW_HINT)
                             if w is not None else _BATCH_CAPACITY)
            f.join_side_rows = (sides[0], sides[1])
        out.append(f)
    return out


# ---------------------------------------------------------------------------
# planned (live runtime) path
# ---------------------------------------------------------------------------

def facts_from_runtime(rt) -> List[QueryFacts]:
    """QueryFacts from a live runtime's compiled plans.  Reads
    `describe()` dicts, plan attributes, and metadata-only state-byte
    accounting — never executes, traces, or fetches device data."""
    from ..core.plan_facts import fusion_exclusion, render_cap
    from ..observability.memory import query_component_bytes

    static_by_name = {f.name: f for f in facts_from_app(rt.app)}
    out: List[QueryFacts] = []
    for name, qr in sorted(rt.query_runtimes.items()):
        q = getattr(qr, "_query_ast", None)
        kind = getattr(qr, "_kind", None) or "plain"
        p = qr.planned
        try:
            desc = p.describe()
        except Exception:  # noqa: BLE001 — diagnostics must not throw
            desc = {}
        comp = query_component_bytes(qr)
        sf = static_by_name.get(name)
        fb = getattr(qr, "_fuse", None)
        f = QueryFacts(
            name=name,
            query=q if q is not None else Query(),
            kind=kind, origin="planned",
            partition=sf.partition if sf is not None else None,
            needs_timer=bool(desc.get("needs_timer",
                                      getattr(p, "needs_timer", False))),
            keyed_window=bool(getattr(p, "keyed_window", False)),
            fuse_requested=(fb.k if fb is not None
                            else getattr(qr, "_fuse_requested", 0)),
            fusion_exclusion=fusion_exclusion(qr),
            emission_cap=render_cap(getattr(p, "compact_rows", None)),
            emission_cap_explicit=bool(getattr(p, "emit_explicit",
                                               False)),
            state_bytes=sum(comp.values()) if comp else None,
            state_bytes_origin="measured",
            key_capacity=int(getattr(p, "key_capacity", 0) or 1),
            nfa_slots=int(getattr(p, "slots", _NFA_SLOTS) or _NFA_SLOTS),
        )
        if sf is not None and sf.join_side_rows is not None:
            f.join_side_rows = sf.join_side_rows
        out.append(f)
    return out
