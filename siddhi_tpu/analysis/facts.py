"""Per-query plan facts for the static analyzer.

Two builders produce the same `QueryFacts` shape:

- `facts_from_app(app)` — pure AST walk plus a *static mini-planner*
  that predicts the facts the real planner would compute (window
  processor class and its `needs_timer`, key/slot capacities, the
  emission-cap sentinel, shape×dtype state-byte estimates) without
  constructing a runtime or touching jax.  Fusion-exclusion reasons are
  NOT re-derived: a shim `planned` carrying the statically-known
  properties is fed through the real `core.fusion.ineligible_reason`,
  so lint reports the exact string the wiring would log at first
  dispatch.

- `facts_from_runtime(rt)` — reads the *actual* planned-query
  dataclasses of a live SiddhiAppRuntime: `describe()` plan facts,
  `core.plan_facts.fusion_exclusion`, and the metadata-only
  `observability.memory` accounting.  Attribute and shape/dtype reads
  only — analysis never executes, traces, or fetches (the lint guard
  test monkeypatches `jax.jit`/`jax.device_get` over a full run).

Query naming mirrors `SiddhiAppRuntime._query_name` exactly (`@info`
name, else `query<i>` numbered across top-level queries and partition
bodies), so findings join against explain/metrics/healthz by name.
"""
from __future__ import annotations

import dataclasses
import types
from typing import Any, Dict, List, Optional, Tuple

from ..query_api.app import SiddhiApp
from ..query_api.query import (
    AbsentStreamStateElement,
    Partition,
    Query,
    RangePartitionType,
    Window,
)

# the static mini-planner's capacity mirrors and AST helpers live in
# core/plan_facts.py so the admission deploy gate shares the EXACT
# implementation (one estimate, one component breakdown — no drift);
# the underscore aliases are this module's historical public names
from ..core.plan_facts import (  # noqa: F401  (re-exported API)
    BATCH_CAPACITY as _BATCH_CAPACITY,
    NFA_SLOTS as _NFA_SLOTS,
    PARTITION_KEYS as _PARTITION_KEYS,
    PARTITION_WINDOW_HINT as _PARTITION_WINDOW_HINT,
    ROW_OVERHEAD as _ROW_OVERHEAD,
    WINDOW_HINT as _WINDOW_HINT,
    capacity_annotation,
    iter_named_queries,
    pattern_atoms,
    query_kind,
    query_state_components,
    row_bytes as _row_bytes,
    window_capacity,
    window_handler,
)


@dataclasses.dataclass
class QueryFacts:
    """What the analyzer knows about one query, from either builder."""

    name: str
    query: Query
    kind: str                           # plain | pattern | join
    origin: str = "static"              # static | planned
    partition: Optional[Partition] = None
    needs_timer: bool = False
    keyed_window: bool = False
    fuse_requested: int = 0
    fusion_exclusion: Optional[str] = None
    # rendered emission cap (None = uncapped / capacity-bounded)
    emission_cap: Optional[int] = None
    emission_cap_explicit: bool = False
    # per-query device state, bytes (shape×dtype arithmetic), with the
    # per-component breakdown MEM001 and the admission deploy gate both
    # cite (static: plan_facts estimator; runtime: measured accounting)
    state_bytes: Optional[int] = None
    state_components: Optional[Dict[str, int]] = None
    state_bytes_origin: str = "estimated"   # estimated | measured
    key_capacity: int = 1
    nfa_slots: int = _NFA_SLOTS
    # join sides: (left rows, right rows) worst-case resident window rows
    join_side_rows: Optional[Tuple[int, int]] = None

    def pos(self) -> Optional[Tuple[int, int]]:
        return getattr(self.query, "pos", None)


@dataclasses.dataclass
class AnalysisContext:
    """Everything a rule may look at."""

    app: SiddhiApp
    queries: List[QueryFacts]
    config: Any = None                  # registry.LintConfig
    source_name: str = "<app>"
    runtime: Any = None                 # live SiddhiAppRuntime, or None


# ---------------------------------------------------------------------------
# shared AST helpers (used by facts builders AND rules)
# ---------------------------------------------------------------------------

def window_needs_timer(win: Optional[Window]) -> bool:
    """needs_timer of the processor class the planner would pick —
    resolved from the live WINDOW_TYPES registry, never re-listed here."""
    if win is None:
        return False
    from ..core.window import WINDOW_TYPES
    full = (win.namespace + ":" if win.namespace else "") + win.name
    cls = WINDOW_TYPES.get(full)
    return bool(getattr(cls, "needs_timer", False)) if cls else False


def fuse_requested(app: SiddhiApp, q: Query) -> int:
    """@fuse on the query, any input stream definition, or @app:fuse.
    Returns K (0 = off).  Delegates to core.plan_facts.fuse_depth — the
    one implementation runtime wiring and the merge planner also use."""
    from ..core.plan_facts import fuse_depth
    return fuse_depth(app, q)


def emit_annotation_rows(q: Query) -> Optional[int]:
    ann = q.get_annotation("emit")
    if ann is None:
        return None
    v = ann.element("rows")
    return int(v) if v is not None else None


# ---------------------------------------------------------------------------
# static path
# ---------------------------------------------------------------------------

def _static_exclusion(app: SiddhiApp, q: Query, kind: str,
                      part: Optional[Partition],
                      needs_timer: bool, keyed: bool) -> Optional[str]:
    """Feed statically-known plan properties through the REAL
    core.fusion.ineligible_reason via a shim `planned`, so the string
    lint prints is the one the wiring would log.  Mesh sharding is a
    deploy-time property (unknowable from source), so the static path
    assumes unsharded — the runtime path reports the sharded reasons."""
    from ..core import fusion
    ist = q.input_stream
    present = object()      # stands in for "this step/body exists"
    if kind == "plain":
        range_part = part is not None and any(
            isinstance(pt, RangePartitionType)
            for pt in part.partition_type_map.values())
        planned = types.SimpleNamespace(
            needs_timer=needs_timer, keyed_window=keyed,
            partition_key_fn=present if range_part else None,
            raw_step=present)
    elif kind == "pattern":
        has_absent = any(
            isinstance(a, AbsentStreamStateElement)
            for a in pattern_atoms(ist.state_element))
        planned = types.SimpleNamespace(
            timer_step=present if has_absent else None,
            partition_positions={"_": [0]} if part is not None else None,
            mesh=None, step_bodies=present)
    else:
        planned = types.SimpleNamespace(
            needs_timer=needs_timer,
            step_left=present, raw_left=present,
            step_right=present, raw_right=present)
    try:
        return fusion.ineligible_reason(
            types.SimpleNamespace(planned=planned), kind)
    except Exception:  # noqa: BLE001 — a shim gap must not kill lint
        return None


def facts_from_app(app: SiddhiApp) -> List[QueryFacts]:
    # merge-aware static estimate (core/plan_facts): a window buffer the
    # multi-query optimizer will share across a group appears ONCE under
    # its `merged:<group>` owner, so per-query facts carry exclusive
    # bytes only and totals (ADM001) agree with the deploy gate
    from ..core.plan_facts import static_state_components
    try:
        merged_comps = static_state_components(app)
    except Exception:  # noqa: BLE001 — estimator must not kill lint
        merged_comps = None
    out: List[QueryFacts] = []
    for name, q, part in iter_named_queries(app):
        kind = query_kind(q)
        caps = capacity_annotation(q, part)
        keys = caps.get("keys", _PARTITION_KEYS)
        win = None
        if kind == "plain":
            win = window_handler(q.input_stream)
            needs_timer = window_needs_timer(win)
            session_keyed = win is not None and win.name == "session" \
                and len(win.parameters) >= 2
            keyed = session_keyed or (part is not None and win is not None)
        elif kind == "join":
            needs_timer = any(
                window_needs_timer(window_handler(s))
                for s in (q.input_stream.left_input_stream,
                          q.input_stream.right_input_stream))
            keyed = False
        else:
            needs_timer = any(
                isinstance(a, AbsentStreamStateElement)
                for a in pattern_atoms(q.input_stream.state_element))
            keyed = False

        from ..core.plan_facts import UNCAPPED_SENTINEL, render_cap
        emit_rows = emit_annotation_rows(q)
        cap = None
        explicit = emit_rows is not None
        if kind == "pattern":
            cap = render_cap(
                emit_rows if explicit
                else (8 if part is not None else UNCAPPED_SENTINEL))
        elif kind == "join":
            cap = render_cap(emit_rows) if explicit else None

        k = fuse_requested(app, q)
        # the ONE static estimator shared with the admission deploy gate
        # (core/plan_facts.query_state_components; merge-aware when the
        # app-level pass computed — the merged view drops a shared
        # window from members and reports it under the group owner)
        if merged_comps is not None:
            comps = merged_comps.get(name, {})
        else:
            comps = query_state_components(app, q, kind, part, caps,
                                           keys)
        f = QueryFacts(
            name=name, query=q, kind=kind, origin="static",
            partition=part, needs_timer=needs_timer, keyed_window=keyed,
            fuse_requested=k,
            fusion_exclusion=_static_exclusion(
                app, q, kind, part, needs_timer, keyed) if k else None,
            emission_cap=cap, emission_cap_explicit=explicit,
            state_bytes=sum(comps.values()) if comps else None,
            state_components=comps or None,
            state_bytes_origin="estimated",
            key_capacity=keys if (part is not None or keyed) else 1,
            nfa_slots=caps.get("slots", _NFA_SLOTS),
        )
        if kind == "join":
            defs = app.stream_definition_map
            sides = []
            for sis in (q.input_stream.left_input_stream,
                        q.input_stream.right_input_stream):
                w = window_handler(sis)
                sides.append(window_capacity(w, _WINDOW_HINT)
                             if w is not None else _BATCH_CAPACITY)
            f.join_side_rows = (sides[0], sides[1])
        out.append(f)
    return out


# ---------------------------------------------------------------------------
# planned (live runtime) path
# ---------------------------------------------------------------------------

def facts_from_runtime(rt) -> List[QueryFacts]:
    """QueryFacts from a live runtime's compiled plans.  Reads
    `describe()` dicts, plan attributes, and metadata-only state-byte
    accounting — never executes, traces, or fetches device data."""
    from ..core.plan_facts import fusion_exclusion, render_cap
    from ..observability.memory import query_component_bytes

    static_by_name = {f.name: f for f in facts_from_app(rt.app)}
    out: List[QueryFacts] = []
    for name, qr in sorted(rt.query_runtimes.items()):
        q = getattr(qr, "_query_ast", None)
        kind = getattr(qr, "_kind", None) or "plain"
        p = qr.planned
        try:
            desc = p.describe()
        except Exception:  # noqa: BLE001 — diagnostics must not throw
            desc = {}
        comp = query_component_bytes(qr)
        sf = static_by_name.get(name)
        fb = getattr(qr, "_fuse", None)
        f = QueryFacts(
            name=name,
            query=q if q is not None else Query(),
            kind=kind, origin="planned",
            partition=sf.partition if sf is not None else None,
            needs_timer=bool(desc.get("needs_timer",
                                      getattr(p, "needs_timer", False))),
            keyed_window=bool(getattr(p, "keyed_window", False)),
            fuse_requested=(fb.k if fb is not None
                            else getattr(qr, "_fuse_requested", 0)),
            fusion_exclusion=fusion_exclusion(qr),
            emission_cap=render_cap(getattr(p, "compact_rows", None)),
            emission_cap_explicit=bool(getattr(p, "emit_explicit",
                                               False)),
            state_bytes=sum(comp.values()) if comp else None,
            state_components=dict(comp) if comp else None,
            state_bytes_origin="measured",
            key_capacity=int(getattr(p, "key_capacity", 0) or 1),
            nfa_slots=int(getattr(p, "slots", _NFA_SLOTS) or _NFA_SLOTS),
        )
        if sf is not None and sf.join_side_rows is not None:
            f.join_side_rows = sf.join_side_rows
        out.append(f)
    return out
