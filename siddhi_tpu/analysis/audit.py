"""siddhi-audit: compiled-plan cost fingerprints + baseline regression gate.

ROADMAP item 3 asks perf PRs to be gated "on flops/bytes from EXPLAIN,
not wall-clock alone" — this module is that gate.  For every app in the
audit corpus (analysis/corpus.py: the shipped samples + the
flagship/windowed_join/block-NFA bench shapes) it extracts a per-query
**plan fingerprint**:

- per compiled step: XLA flops, bytes accessed, transcendentals,
  argument/output/temp/peak memory, collective ops in the HLO, and the
  argument signature it was graded at;
- per query: hot-path totals, dispatch-program count, recompile
  signature arity (how many distinct programs first traffic will
  trace), state bytes by component, emission caps, fusion eligibility
  (+ the concrete exclusion reason), and the static type/null-flow
  summary (analysis/typeflow.py).

Extraction is the EXPLAIN re-lowering path (observability/explain.py
`step_cost`) fed with canonical synthesized signatures
(analysis/signatures.py) and run under `RECOMPILES.suppress()`: the
audit plans and lowers but NEVER dispatches a step, sends traffic, or
fetches device memory — `tests/test_audit.py` enforces all three.

`diff_fingerprints` grades a fresh extraction against the checked-in
`PLAN_BASELINE.json` with per-metric relative tolerances: cost-metric
*increases* beyond tolerance are regressions (decreases are reported as
improvements worth a baseline update), and structural facts — signature,
collectives, caps, fusion, state components, types — must match
exactly.  Exit-code contract (CLI in tools/audit.py): 0 clean,
1 regression, 2 error.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Tuple

BASELINE_VERSION = 1
DEFAULT_BASELINE = "PLAN_BASELINE.json"

# ---------------------------------------------------------------------------
# metric catalog — docgen renders this table; tolerances are RELATIVE
# (0.05 = +5% passes, more fails).  `gate`:
#   increase  — fail when current > baseline * (1 + tol)
#   exact     — any change fails (structural contract)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Metric:
    name: str
    tolerance: float
    gate: str            # 'increase' | 'exact'
    description: str


METRICS: List[Metric] = [
    Metric("flops", 0.05, "increase",
           "XLA cost_analysis flops per dispatch of the step program"),
    Metric("transcendentals", 0.05, "increase",
           "transcendental op count per dispatch"),
    Metric("bytes_accessed", 0.05, "increase",
           "XLA cost_analysis bytes accessed per dispatch — the "
           "bandwidth-bound hot paths live and die on this"),
    Metric("argument_bytes", 0.02, "increase",
           "bytes of device arguments the compiled step binds"),
    Metric("output_bytes", 0.05, "increase",
           "bytes of device outputs per dispatch"),
    Metric("temp_bytes", 0.25, "increase",
           "XLA temp allocation per dispatch (scheduler-sensitive, "
           "hence the loose tolerance)"),
    Metric("peak_bytes", 0.25, "increase",
           "argument+output+temp-alias live-at-once estimate"),
    Metric("state_bytes", 0.0, "increase",
           "per-component device state (shape×dtype arithmetic — "
           "deterministic, so zero tolerance)"),
    Metric("collectives", 0.0, "exact",
           "collective-op kinds in the compiled step HLO (sharded "
           "plans) — a new collective is a new mesh synchronization"),
    Metric("signature", 0.0, "exact",
           "canonical argument signature the step was graded at"),
    Metric("dispatch_programs", 0.0, "exact",
           "XLA programs one steady-state batch dispatches"),
    Metric("recompile_signature_arity", 0.0, "exact",
           "distinct step programs first traffic will trace (compile "
           "storms scale with this)"),
    Metric("emission_cap", 0.0, "exact",
           "per-dispatch emission row cap (None = uncapped sentinel)"),
    Metric("fusion", 0.0, "exact",
           "@fuse eligibility / active K / concrete exclusion reason"),
    Metric("types", 0.0, "exact",
           "static output column types + nullable set (typeflow pass)"),
    Metric("equi_fastpath", 0.0, "exact",
           "equi-join fast-path mode / key attrs / lane capacity (or "
           "the inapplicability reason) — a silently deactivated fast "
           "path is a 10-100x regression the float metrics would also "
           "catch, this names the cause"),
    Metric("merge", 0.0, "exact",
           "multi-query merge fact: group membership, shared/stacked "
           "mode, and the group's dispatch-program count (or the "
           "planner's ineligibility reason) — an accidentally unmerged "
           "group re-pays N dispatches per batch, which wall-clock "
           "benchmarks would catch late and this pins in CI"),
]

DEFAULT_TOLERANCES: Dict[str, float] = {m.name: m.tolerance
                                        for m in METRICS}

_STEP_FLOAT_METRICS = ("flops", "transcendentals", "bytes_accessed")
_MEM_FLOAT_METRICS = ("argument_bytes", "output_bytes", "temp_bytes",
                      "peak_bytes")


# ---------------------------------------------------------------------------
# fingerprint extraction
# ---------------------------------------------------------------------------

def _merge_fact(qr) -> Dict:
    """Multi-query-optimizer fact pinned per query (core/plan_facts.
    merge_facts): group membership + mode when merged, the exact
    ineligibility reason otherwise.  Exact-match gated — an accidental
    unmerge flips `merged` and fails the build."""
    from ..core.plan_facts import merge_facts
    try:
        return merge_facts(qr)
    except Exception:  # noqa: BLE001 — extraction must not kill audit
        return {"merged": False}


def query_fingerprint(rt, qname: str, typeflow_summary: Optional[Dict]
                      = None, collectives: bool = False) -> Dict:
    """One query's plan fingerprint from a live (never-run) runtime."""
    from ..core.plan_facts import render_cap
    from ..core import fusion as _fusion
    from ..observability.explain import _runtime_kind, _steps_of, \
        step_cost
    from ..observability.memory import query_component_bytes
    from .signatures import primary_roles, synthesize

    qr = rt.query_runtimes[qname]
    kind = _runtime_kind(qr)
    synth = synthesize(qr, kind)
    cache = rt.__dict__.setdefault("_explain_cost_cache", {})
    mesh = getattr(qr, "mesh", None) or getattr(qr, "keyed_mesh", None)
    want_coll = collectives or mesh is not None
    steps: Dict[str, Dict] = {}
    for role, fn in _steps_of(qr, kind):
        c = step_cost(fn, cache, deep=True, specs=synth.get(role),
                      collectives=want_coll)
        if not c.get("available"):
            continue
        entry: Dict[str, Any] = {
            "signature": c.get("signature"),
            "flops": c.get("flops", 0.0),
            "transcendentals": c.get("transcendentals", 0.0),
            "bytes_accessed": c.get("bytes_accessed", 0.0),
        }
        mem = c.get("memory") or {}
        for k in _MEM_FLOAT_METRICS:
            entry[k] = mem.get(k, 0)
        if want_coll:
            entry["collectives"] = c.get("collectives", [])
        steps[role] = entry

    primaries = [r for r in primary_roles(qr, kind) if r in steps]
    totals = {
        k: sum(steps[r].get(k, 0) or 0 for r in primaries)
        for k in ("flops", "bytes_accessed")
    }
    totals["peak_bytes"] = max(
        (steps[r].get("peak_bytes", 0) or 0 for r in primaries),
        default=0)
    comp = query_component_bytes(qr)
    p = qr.planned
    coll_kinds = sorted({c for s in steps.values()
                         for c in s.get("collectives", ())})
    fp: Dict[str, Any] = {
        "kind": kind,
        "steps": steps,
        "totals": totals,
        "dispatch_programs": len(primaries),
        "recompile_signature_arity": len(steps),
        "collective_kinds": coll_kinds,
        "collective_steps": sum(1 for s in steps.values()
                                if s.get("collectives")),
        "state": {"components": dict(comp),
                  "total_bytes": sum(comp.values())},
        "emission": {
            "cap_rows": render_cap(getattr(p, "compact_rows", None)),
            "cap_explicit": bool(getattr(p, "emit_explicit", False)),
        },
        "fusion": _fusion.eligibility(qr, kind),
        "merge": _merge_fact(qr),
    }
    if hasattr(p, "fastpath_facts"):
        fp["equi_fastpath"] = p.fastpath_facts()
    if typeflow_summary is not None:
        fp["types"] = typeflow_summary
    return fp


def app_fingerprint(rt, collectives: bool = False) -> Dict[str, Dict]:
    """{query: fingerprint} for every query of a (never-run) runtime."""
    from .typeflow import infer_app, summarize
    try:
        flows = infer_app(rt.app).queries
    except Exception:  # noqa: BLE001 — inference must not block audit
        flows = {}
    out = {}
    for qname in sorted(rt.query_runtimes):
        tf = flows.get(qname)
        out[qname] = query_fingerprint(
            rt, qname,
            typeflow_summary=summarize(tf) if tf is not None else None,
            collectives=collectives)
    return out


def _mesh_of(n: int):
    import numpy as np
    if n <= 1:
        return None
    import jax
    from jax.sharding import Mesh
    devs = jax.devices()
    if len(devs) < n:
        return False          # environment cannot build this shape
    return Mesh(np.array(devs[:n]), ("shard",))


def corpus_fingerprints(samples_dir: Optional[str] = None,
                        include_bench: bool = True,
                        ) -> Tuple[Dict[str, Dict], List[str]]:
    """Fingerprint the whole corpus.  Returns ({corpus key:
    {devices, queries}}, [skipped keys]) — a shape is skipped (not
    failed) when the environment lacks the devices it needs."""
    from .. import SiddhiManager
    from .corpus import corpus as _corpus

    out: Dict[str, Dict] = {}
    skipped: List[str] = []
    for key, ql, devices in _corpus(samples_dir, include_bench):
        mesh = _mesh_of(devices)
        if mesh is False:
            skipped.append(key)
            continue
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(ql, mesh=mesh) \
                if mesh is not None else m.create_siddhi_app_runtime(ql)
            entry = {"devices": devices,
                     "queries": app_fingerprint(
                         rt, collectives=devices > 1)}
            if devices > 1:
                key = f"{key}@{devices}"
            out[key] = entry
        finally:
            m.shutdown()
    return out, skipped


def environment() -> Dict[str, str]:
    import jax
    import jaxlib
    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__,
            "backend": jax.default_backend()}


def build_baseline(samples_dir: Optional[str] = None,
                   include_bench: bool = True,
                   tolerances: Optional[Dict[str, float]] = None
                   ) -> Dict:
    fps, skipped = corpus_fingerprints(samples_dir, include_bench)
    return {
        "version": BASELINE_VERSION,
        "generated_by": "python -m siddhi_tpu.tools.audit update",
        "environment": environment(),
        "tolerances": dict(tolerances or DEFAULT_TOLERANCES),
        "skipped_at_update": skipped,
        "corpus": fps,
    }


# ---------------------------------------------------------------------------
# baseline diff
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Delta:
    """One comparison outcome.  level: regression | improvement | note."""

    level: str
    shape: str
    query: Optional[str]
    metric: str
    message: str
    role: Optional[str] = None
    baseline: Any = None
    current: Any = None

    def render(self) -> str:
        where = self.shape + (f":{self.query}" if self.query else "") + \
            (f" [{self.role}]" if self.role else "")
        return f"{self.level.upper():11s} {where} {self.metric}: " \
               f"{self.message}"

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


def _rel(base: float, cur: float) -> float:
    if not base:
        return float("inf") if cur else 0.0
    return (cur - base) / abs(base)


def _cmp_number(out: List[Delta], shape: str, query: Optional[str],
                role: Optional[str], metric: str, base, cur,
                tol: float) -> None:
    base = float(base or 0)
    cur = float(cur or 0)
    if base == cur:
        return
    r = _rel(base, cur)
    pct = f"{r * 100:+.1f}%"
    msg = f"{base:,.0f} -> {cur:,.0f} ({pct}, tolerance " \
          f"±{tol * 100:.0f}%)"
    if r > tol:
        out.append(Delta("regression", shape, query, metric, msg, role,
                         base, cur))
    elif r < -tol:
        out.append(Delta("improvement", shape, query, metric, msg, role,
                         base, cur))


def _cmp_exact(out: List[Delta], shape: str, query: Optional[str],
               role: Optional[str], metric: str, base, cur) -> None:
    if base != cur:
        out.append(Delta("regression", shape, query, metric,
                         f"{base!r} -> {cur!r} (exact-match contract)",
                         role, base, cur))


def diff_fingerprints(baseline: Dict, current: Dict[str, Dict],
                      skipped: Optional[List[str]] = None,
                      tolerances: Optional[Dict[str, float]] = None
                      ) -> List[Delta]:
    """Grade `current` corpus fingerprints against a loaded baseline."""
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(baseline.get("tolerances") or {})
    tol.update(tolerances or {})
    out: List[Delta] = []
    base_corpus: Dict[str, Dict] = baseline.get("corpus", {})
    skipped = list(skipped or ())

    for shape in sorted(set(base_corpus) | set(current)):
        b, c = base_corpus.get(shape), current.get(shape)
        if c is None:
            if any(shape.startswith(f"{s}@") or shape == s
                   for s in skipped):
                out.append(Delta("note", shape, None, "devices",
                                 "skipped: environment has too few "
                                 "devices for this shape"))
            else:
                out.append(Delta("regression", shape, None, "corpus",
                                 "shape in baseline but not produced "
                                 "by this checkout"))
            continue
        if b is None:
            out.append(Delta("regression", shape, None, "corpus",
                             "unbaselined shape — run `python -m "
                             "siddhi_tpu.tools.audit update`"))
            continue
        bq, cq = b.get("queries", {}), c.get("queries", {})
        for q in sorted(set(bq) | set(cq)):
            if q not in cq:
                out.append(Delta("regression", shape, q, "corpus",
                                 "query disappeared from the plan"))
                continue
            if q not in bq:
                out.append(Delta("regression", shape, q, "corpus",
                                 "unbaselined query — run update"))
                continue
            _diff_query(out, shape, q, bq[q], cq[q], tol)
    return out


def _diff_query(out: List[Delta], shape: str, q: str, b: Dict, c: Dict,
                tol: Dict[str, float]) -> None:
    bsteps, csteps = b.get("steps", {}), c.get("steps", {})
    for role in sorted(set(bsteps) | set(csteps)):
        if role not in csteps:
            out.append(Delta("regression", shape, q, "steps",
                             "compiled step variant disappeared",
                             role))
            continue
        if role not in bsteps:
            out.append(Delta("regression", shape, q, "steps",
                             "new compiled step variant (unbaselined)",
                             role))
            continue
        bs, cs = bsteps[role], csteps[role]
        for m in _STEP_FLOAT_METRICS + _MEM_FLOAT_METRICS:
            _cmp_number(out, shape, q, role, m, bs.get(m), cs.get(m),
                        tol.get(m, 0.0))
        _cmp_exact(out, shape, q, role, "signature",
                   bs.get("signature"), cs.get("signature"))
        bcoll = bs.get("collectives", []) or []
        ccoll = cs.get("collectives", []) or []
        added = sorted(set(ccoll) - set(bcoll))
        removed = sorted(set(bcoll) - set(ccoll))
        if added:
            out.append(Delta("regression", shape, q, "collectives",
                             f"new collective op(s) {added} in the "
                             "step HLO", role, bcoll, ccoll))
        if removed:
            out.append(Delta("improvement", shape, q, "collectives",
                             f"collective op(s) {removed} no longer "
                             "emitted", role, bcoll, ccoll))
    # per-component state bytes
    bc = (b.get("state") or {}).get("components", {})
    cc = (c.get("state") or {}).get("components", {})
    for comp in sorted(set(bc) | set(cc)):
        if comp not in cc or comp not in bc:
            _cmp_exact(out, shape, q, None, "state_bytes",
                       {comp: bc.get(comp)}, {comp: cc.get(comp)})
            continue
        _cmp_number(out, shape, q, comp, "state_bytes", bc[comp],
                    cc[comp], tol.get("state_bytes", 0.0))
    # structural facts
    for metric, path in (
            ("dispatch_programs", "dispatch_programs"),
            ("recompile_signature_arity", "recompile_signature_arity"),
            ("collectives", "collective_kinds"),
            ("emission_cap", "emission"),
            ("fusion", "fusion"),
            ("equi_fastpath", "equi_fastpath"),
            ("merge", "merge"),
            ("types", "types")):
        _cmp_exact(out, shape, q, None, metric, b.get(path),
                   c.get(path))


# ---------------------------------------------------------------------------
# load / save
# ---------------------------------------------------------------------------

def baseline_path(path: Optional[str] = None) -> str:
    from .corpus import repo_root
    if path:
        return path
    return os.path.join(repo_root(), DEFAULT_BASELINE)


def load_baseline(path: Optional[str] = None) -> Dict:
    p = baseline_path(path)
    with open(p, "r") as fh:
        b = json.load(fh)
    v = b.get("version")
    if v != BASELINE_VERSION:
        raise ValueError(f"baseline {p} has version {v!r}; this build "
                         f"expects {BASELINE_VERSION} — regenerate with "
                         "`python -m siddhi_tpu.tools.audit update`")
    return b


def save_baseline(baseline: Dict, path: Optional[str] = None) -> str:
    p = baseline_path(path)
    tmp = p + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(baseline, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, p)
    return p


def has_regressions(deltas: List[Delta]) -> bool:
    return any(d.level == "regression" for d in deltas)
