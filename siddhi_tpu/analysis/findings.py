"""Finding model for the static plan analyzer.

A Finding is one diagnostic: a stable rule ID (`STATE001`), a severity,
a human message, an optional source location (`app.siddhi:3:9` — from
the parser's position threading), the query/component it concerns, and a
fix hint.  Findings are plain data — JSON-able for the REST surface and
renderable as one text line for the CLI.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

INFO = "INFO"
WARN = "WARN"
ERROR = "ERROR"
SEVERITIES = (INFO, WARN, ERROR)

_RANK = {s: i for i, s in enumerate(SEVERITIES)}


def severity_rank(severity: str) -> int:
    """INFO=0 < WARN=1 < ERROR=2; unknown severities rank as ERROR so a
    typo'd override fails closed, not open."""
    return _RANK.get(str(severity).upper(), _RANK[ERROR])


@dataclasses.dataclass
class Finding:
    rule_id: str
    severity: str                       # INFO | WARN | ERROR
    message: str
    query: Optional[str] = None         # query / component name
    pos: Optional[Tuple[int, int]] = None   # (line, col), 1-based
    source: Optional[str] = None        # file name or '<app>'
    hint: Optional[str] = None

    def location(self) -> str:
        """`app.siddhi:3:9` (falls back to the bare source name when the
        AST node carried no position)."""
        src = self.source or "<app>"
        if self.pos:
            return f"{src}:{self.pos[0]}:{self.pos[1]}"
        return src

    def to_dict(self) -> Dict:
        d: Dict = {
            "rule": self.rule_id,
            "severity": self.severity,
            "message": self.message,
            "location": self.location(),
        }
        if self.query is not None:
            d["query"] = self.query
        if self.pos is not None:
            d["line"], d["col"] = int(self.pos[0]), int(self.pos[1])
        if self.hint:
            d["hint"] = self.hint
        return d

    def render(self) -> str:
        """One CLI text line:
        `app.siddhi:3:9: WARN STATE001 [query] message (fix: hint)`."""
        parts = [f"{self.location()}: {self.severity} {self.rule_id}"]
        if self.query:
            parts.append(f"[{self.query}]")
        parts.append(self.message)
        line = " ".join(parts)
        if self.hint:
            line += f" (fix: {self.hint})"
        return line

    def sort_key(self):
        """Most severe first, then source order, then rule id — the
        driver sorts with this so text, JSON, and golden outputs are
        deterministic."""
        return (-severity_rank(self.severity),
                self.pos or (1 << 30, 0),
                self.rule_id,
                self.query or "")


def counts(findings) -> Dict[str, int]:
    out = {s: 0 for s in SEVERITIES}
    for f in findings:
        out[f.severity] = out.get(f.severity, 0) + 1
    return out
