"""The audit corpus: every app whose compiled plan is fingerprinted.

Two sources:

- `samples/apps/*.siddhi` — the shipped sample corpus (`make lint-apps`
  already keeps it zero-ERROR; the auditor additionally pins each app's
  cost fingerprint).
- The three bench serving shapes ROADMAP gates perf PRs on — flagship
  (partitioned 4-state pattern), windowed_join (the 100× outlier item 2
  names), and the block-NFA sequence — defined HERE and imported by
  `bench.py`, so the shapes the benchmark drives and the shapes the
  audit gate pins are one set of strings that cannot drift.

Templates keep bench's historical placeholder names ({async_ann},
{pipe_ann}, {n_keys}, {slots}, {ann}, {keys}) so bench call sites
format them unchanged.
"""
from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# bench shape templates (single source — bench.py imports these)
# ---------------------------------------------------------------------------

# the flagship serving shape: partitioned 4-stage NFA over a key space
# (bench.py run_tpu / --mode multichip drive it at different capacities)
FLAGSHIP_QL_TEMPLATE = """
@app:playback
{async_ann}
define stream TradeStream (key long, price float, volume int);
partition with (key of TradeStream)
begin
  @capacity(keys='{n_keys}', slots='{slots}')
  @emit(rows='2')
  {pipe_ann}
  @info(name='flagship')
  from every e1=TradeStream[volume == 1]
       -> e2=TradeStream[volume == 2 and price >= e1.price]
       -> e3=TradeStream[volume == 3]
       -> e4=TradeStream[volume == 4 and price >= e3.price]
  select e1.key as k, e1.price as p1, e2.price as p2, e4.price as p4
  insert into Matches;
end;
"""

# multichip variant: same NFA with @fuse riding the mesh (bench
# _mc_flagship); kept as its own template because the @fuse annotation
# changes the compiled artifact set (shard_fused_steps)
MC_FLAGSHIP_QL = """
@app:playback
define stream TradeStream (key long, price float, volume int);
partition with (key of TradeStream)
begin
  @capacity(keys='{keys}', slots='4')
  @emit(rows='2')
  @fuse(batches='4')
  @info(name='flagship')
  from every e1=TradeStream[volume == 1]
       -> e2=TradeStream[volume == 2 and price >= e1.price]
       -> e3=TradeStream[volume == 3]
       -> e4=TradeStream[volume == 4 and price >= e3.price]
  select e1.key as k, e1.price as p1, e2.price as p2, e4.price as p4
  insert into Matches;
end;
"""

# the 100x outlier: two-stream windowed join evaluated as a full [R,C]
# grid today (ROADMAP item 2 / lint JOIN002 cite this shape)
WINDOWED_JOIN_QL = """
@app:playback
define stream L (symbol long, price float);
define stream R (symbol long, qty int);
@emit(rows='65536')
@info(name='q')
from L#window.length(128) join R#window.length(128)
  on L.symbol == R.symbol
select L.symbol as s, L.price as p, R.qty as v
insert into Out;
"""

# multichip join variant (bench _mc_windowed_join — GSPMD placement)
MC_JOIN_QL = """
@app:playback
define stream JL (sym long, price float);
define stream JR (sym long, qty int);
@emit(rows='65536')
@info(name='wjoin')
from JL#window.length(64) join JR#window.length(64)
  on JL.sym == JR.sym
select JL.sym as s, JL.price as p, JR.qty as q
insert into JOut;
"""

# single-key block-NFA sequence (VERDICT §9 shape 2; bench
# sequence_within / _mc_block_nfa)
SEQUENCE_QL = """
@app:playback
define stream S (symbol long, price float, volume int);
@capacity(keys='1', slots='8')
@emit(rows='4096')
{ann}
@info(name='q')
from every e1=S[volume == 1], e2=S[volume == 2 and price > e1.price]
  within 1 sec
select e1.price as p1, e2.price as p2
insert into M;
"""


# ---------------------------------------------------------------------------
# the audited corpus
# ---------------------------------------------------------------------------

def bench_shapes() -> List[Tuple[str, str, int]]:
    """(corpus key, SiddhiQL, mesh devices) for the bench shapes the
    audit baseline pins.  `mesh devices` 1 = single device; the sharded
    flagship entry is what surfaces collectives in the step HLO (skipped
    with a note when the environment has fewer devices)."""
    return [
        ("bench/flagship",
         FLAGSHIP_QL_TEMPLATE.format(async_ann="", pipe_ann="",
                                     n_keys=512, slots=4), 1),
        ("bench/windowed_join", WINDOWED_JOIN_QL, 1),
        ("bench/block_nfa", SEQUENCE_QL.format(ann=""), 1),
        # the served variant bench --mode serve_compare drives: same NFA
        # with emissions routed through the device ring (its fingerprint
        # pins the serve_ring state component plan_facts adds)
        ("bench/block_nfa_served",
         SEQUENCE_QL.format(ann="@serve\n@fuse(batches='8')"), 1),
        ("bench/flagship_sharded", MC_FLAGSHIP_QL.format(keys=512), 4),
    ]


def sample_apps(samples_dir: Optional[str] = None) -> Dict[str, str]:
    """{corpus key: SiddhiQL} for every shipped sample app."""
    if samples_dir is None:
        samples_dir = os.path.join(repo_root(), "samples", "apps")
    out: Dict[str, str] = {}
    for path in sorted(glob.glob(os.path.join(samples_dir,
                                              "*.siddhi"))):
        name = os.path.splitext(os.path.basename(path))[0]
        with open(path, "r") as fh:
            out[f"samples/{name}"] = fh.read()
    return out


def repo_root() -> str:
    """The repository root (two levels above this package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def corpus(samples_dir: Optional[str] = None,
           include_bench: bool = True) -> List[Tuple[str, str, int]]:
    """Ordered (key, ql, mesh devices) over the full audited corpus."""
    out = [(k, ql, 1) for k, ql in sample_apps(samples_dir).items()]
    if include_bench:
        out += bench_shapes()
    return out
