"""Static plan analyzer (`siddhi-lint`): rule-based TPU-hazard detection
over the parsed AST and planned-query dataclasses, without executing or
tracing anything.

Reference (what): the reference validates apps structurally at parse
time (SiddhiAppValidator) but has no hazard lint; everything
TPU-specific in this engine — unbounded pattern state, fusion-ineligible
@fuse, emission-cap truncation, device-state blowup — previously
surfaced only at runtime through the observability layer.  TPU design
(how): the plan IS static here (state shapes, caps, and step wiring are
all decided before the first event), so a pre-deploy pass can read the
same plan facts explain() reports and flag the hazard before CI ships
the app.

Surfaces: `python -m siddhi_tpu.tools.lint app.siddhi`,
`runtime.analyze()`, `GET /siddhi-apps/<app>/lint`, and findings echoed
into `explain()` reports.

The package also hosts the plan auditor (`analysis/audit.py` +
`python -m siddhi_tpu.tools.audit`): per-query compiled-plan cost
fingerprints (flops/bytes/memory/collectives via the EXPLAIN
re-lowering path at canonical synthesized signatures —
`analysis/signatures.py`) diffed against the checked-in
PLAN_BASELINE.json, and the expression type/null-flow inference pass
(`analysis/typeflow.py`) that NULL001/JOIN002 and the fingerprints
consume.
"""
from .driver import analyze, report
from .findings import ERROR, INFO, SEVERITIES, WARN, Finding, counts, \
    severity_rank
from .registry import RULES, LintConfig, Rule, catalog, rule

__all__ = [
    "analyze", "report", "Finding", "counts", "severity_rank",
    "INFO", "WARN", "ERROR", "SEVERITIES",
    "RULES", "Rule", "rule", "catalog", "LintConfig",
]
