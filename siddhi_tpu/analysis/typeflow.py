"""Expression-level static type & null-flow inference.

Reference (what): the reference type-checks every ExpressionExecutor at
parse time (returnType on each executor) and passes boxed nulls through
every operator.  TPU design (how): our nulls are IN-BAND reserved
values (INT/LONG dtype minimum, FLOAT/DOUBLE NaN, BOOL has no spare
value — PARITY.md "Numeric nulls"), so knowing *which attributes can
actually be null* is a static property worth computing: it decides
where the in-band encoding diverges from reference semantics (a
legitimate INT_MIN decodes as None; a null BOOL decodes as False) and
it is exactly the per-column fact a validity bit-plane (ROADMAP item 5)
would materialize.

This pass walks the parsed app only — no runtime, no jax — and infers
for every AST expression a `TypeInfo(type, nullable, why)`:

- types mirror `core.executor.compile_expression`'s promotion rules
  (the ONE `promote()` implementation is imported, not re-listed);
- nullability ORIGINATES at outer-join non-preserved sides, optional
  pattern atoms (`or` branches, `count` atoms with min 0, absent
  streams), and empty-set aggregations (`min`/`max`/`avg`/`sum`), then
  PROPAGATES through arithmetic, selectors, and inserted-into streams
  to downstream queries (fixpoint over the app's dataflow);
- `coalesce` clears nullability unless every argument is nullable;
  comparisons and boolean operators always yield non-null BOOL (the
  device lowers null compares to false).

Consumers: the plan auditor records per-query output types/nullability
in fingerprints (analysis/audit.py), and lint rule NULL001 flags
nullable INT/LONG/BOOL attributes flowing into compares/arithmetic —
the static half of the ROADMAP item-5 divergence list.  JOIN002 uses
the same query walk to spot equi-join conjuncts (ROADMAP item 2).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Tuple

from ..query_api import expression as ex
from ..query_api.app import SiddhiApp
from ..query_api.query import (
    AbsentStreamStateElement,
    CountStateElement,
    EveryStateElement,
    JoinInputStream,
    LogicalStateElement,
    NextStateElement,
    Query,
    StreamStateElement,
)

# type promotion is the executor's (core/executor.py promote) — imported
# so this pass can never disagree with what the device computes
from ..core.executor import promote as _promote

NUMERIC = ("INT", "LONG", "FLOAT", "DOUBLE")
# in-band null encodings diverge from reference semantics for these
# types: INT/LONG reserve the dtype minimum (a legitimate INT_MIN is
# treated as null), BOOL has no spare value (null decodes as False)
SENTINEL_DIVERGENT = ("INT", "LONG", "BOOL")

# empty-set aggregations return null in the reference (count does not)
_NULLABLE_AGGS = {"min", "max", "avg", "sum", "stdDev", "first", "last",
                  "minForever", "maxForever"}
_AGG_TYPES = {"count": "LONG", "distinctCount": "LONG", "avg": "DOUBLE",
              "stdDev": "DOUBLE"}


@dataclasses.dataclass(frozen=True)
class TypeInfo:
    """Inferred static type of one expression node."""

    type: Optional[str]          # INT|LONG|FLOAT|DOUBLE|BOOL|STRING|OBJECT
    nullable: bool = False
    why: Optional[str] = None    # provenance of the nullability

    def to_dict(self) -> Dict:
        d: Dict = {"type": self.type, "nullable": self.nullable}
        if self.nullable and self.why:
            d["why"] = self.why
        return d


_NOT_NULL_BOOL = TypeInfo("BOOL", False)


@dataclasses.dataclass
class ExprUse:
    """One analyzed expression occurrence inside a query: the AST node,
    its inferred TypeInfo, per-operand infos (for binary nodes), and
    where it sits (filter | select | having | on | group_by)."""

    node: object
    info: TypeInfo
    context: str
    operands: Tuple[TypeInfo, ...] = ()


@dataclasses.dataclass
class QueryTypeFlow:
    """Everything the pass inferred about one query."""

    name: str
    kind: str                                    # plain | join | pattern
    outputs: List[Dict]                          # [{name, type, nullable, why?}]
    uses: List[ExprUse]
    # join only: the ON-condition's top-level equality conjuncts across
    # sides, [(Compare node, left attr, right attr)] — JOIN002's facts
    equi_conjuncts: List[Tuple[object, str, str]] = \
        dataclasses.field(default_factory=list)


@dataclasses.dataclass
class AppTypeFlow:
    """Per-app inference result: stream-attribute nullability (after the
    dataflow fixpoint) and per-query flows."""

    streams: Dict[str, Dict[str, TypeInfo]]
    queries: Dict[str, QueryTypeFlow]


# ---------------------------------------------------------------------------
# variable resolution
# ---------------------------------------------------------------------------

class _Resolver:
    """Resolves Variables for one query against its input sources.

    `sources` maps source key (stream id or pattern ref) to
    (definition, extra-nullability map, why).  Unqualified attribute
    names search all sources in order."""

    def __init__(self, app: SiddhiApp,
                 stream_nulls: Dict[str, Dict[str, TypeInfo]],
                 inferred_types: Optional[Dict[str, Dict[str, str]]]
                 = None):
        self.app = app
        self.stream_nulls = stream_nulls
        # attribute types of INFERRED streams (insert-into targets with
        # no `define stream`), learned from the producing query's
        # selector during the fixpoint
        self.inferred_types = inferred_types or {}
        # key -> (stream_id, source_nullable, why)
        self.sources: List[Tuple[str, str, bool, Optional[str]]] = []
        self.bound: Dict[str, TypeInfo] = {}   # selector output aliases

    def add(self, key: str, stream_id: str, nullable: bool = False,
            why: Optional[str] = None) -> None:
        self.sources.append((key, stream_id, nullable, why))

    def _definition(self, stream_id: str):
        app = self.app
        return (app.stream_definition_map.get(stream_id)
                or app.window_definition_map.get(stream_id)
                or app.table_definition_map.get(stream_id))

    def resolve(self, var: ex.Variable) -> TypeInfo:
        name = var.attribute_name
        order = [s for s in self.sources
                 if var.stream_id in (None, s[0], s[1])] \
            if var.stream_id else list(self.sources)
        for _, sid, src_null, src_why in order:
            d = self._definition(sid)
            t = None
            if d is not None:
                try:
                    t = d.attribute_type(name)
                except (KeyError, AttributeError):
                    t = None
            if t is None:
                t = self.inferred_types.get(sid, {}).get(name)
            if t is None:
                continue
            flow = self.stream_nulls.get(sid, {}).get(name)
            nullable = bool(src_null or (flow and flow.nullable))
            why = src_why if src_null else (flow.why if flow else None)
            return TypeInfo(t, nullable, why)
        if name in self.bound:
            return self.bound[name]
        return TypeInfo(None, False)


# ---------------------------------------------------------------------------
# expression inference
# ---------------------------------------------------------------------------

def infer_expr(e, resolver: _Resolver) -> TypeInfo:
    """TypeInfo of one expression tree (mirrors executor semantics)."""
    if e is None:
        return TypeInfo(None, False)
    if isinstance(e, ex.Constant):
        return TypeInfo(e.type, False)
    if isinstance(e, ex.Variable):
        return resolver.resolve(e)
    if isinstance(e, (ex.Add, ex.Subtract, ex.Multiply, ex.Divide,
                      ex.Mod)):
        li = infer_expr(e.left, resolver)
        ri = infer_expr(e.right, resolver)
        t = _promote(li.type, ri.type) \
            if li.type in NUMERIC and ri.type in NUMERIC else li.type
        n = li.nullable or ri.nullable
        why = li.why if li.nullable else ri.why
        return TypeInfo(t, n, f"arithmetic over nullable operand ({why})"
                        if n else None)
    if isinstance(e, (ex.Compare, ex.And, ex.Or, ex.Not, ex.IsNull,
                      ex.In)):
        # device compare with null is false; boolean ops never yield null
        return _NOT_NULL_BOOL
    if isinstance(e, ex.AttributeFunction):
        return _infer_function(e, resolver)
    return TypeInfo(None, False)


def _const_str(p) -> Optional[str]:
    return p.value if isinstance(p, ex.Constant) and \
        isinstance(p.value, str) else None


def _infer_function(e: ex.AttributeFunction,
                    resolver: _Resolver) -> TypeInfo:
    name = e.name
    params = [infer_expr(p, resolver) for p in e.parameters]
    if name in ("cast", "convert") and len(e.parameters) >= 2:
        target = (_const_str(e.parameters[1]) or "").upper()
        target = {"INTEGER": "INT"}.get(target, target)
        src = params[0]
        if target in NUMERIC + ("STRING", "BOOL", "OBJECT"):
            return TypeInfo(target, src.nullable, src.why)
        return TypeInfo(src.type, src.nullable, src.why)
    if name == "coalesce" and params:
        # first non-null argument: nullable only when EVERY arg is
        t = params[0].type
        for p in params[1:]:
            if t in NUMERIC and p.type in NUMERIC:
                t = _promote(t, p.type)
        if all(p.nullable for p in params):
            return TypeInfo(t, True, params[0].why)
        return TypeInfo(t, False)
    if name == "ifThenElse" and len(params) == 3:
        a, b = params[1], params[2]
        t = _promote(a.type, b.type) \
            if a.type in NUMERIC and b.type in NUMERIC else a.type
        n = a.nullable or b.nullable
        return TypeInfo(t, n, a.why if a.nullable else b.why)
    if name in _AGG_TYPES or name in _NULLABLE_AGGS:
        t = _AGG_TYPES.get(name) or (params[0].type if params else None)
        if name in _NULLABLE_AGGS:
            return TypeInfo(t, True,
                            f"{name}() over an empty set yields null")
        return TypeInfo(t, False)
    if name in ("str", "concat", "upper", "lower", "trim", "UUID",
                "currentTimeMillis"):
        t = "STRING" if name != "currentTimeMillis" else "LONG"
        n = any(p.nullable for p in params)
        return TypeInfo(t, n, next((p.why for p in params
                                    if p.nullable), None))
    # unknown function: type unknown, null flows through
    n = any(p.nullable for p in params)
    return TypeInfo(None, n, next((p.why for p in params
                                   if p.nullable), None))


# ---------------------------------------------------------------------------
# per-query source wiring (where nullability ORIGINATES)
# ---------------------------------------------------------------------------

def _optional_pattern_refs(el, optional: bool = False
                           ) -> Iterator[Tuple[str, str, bool, str]]:
    """(ref key, stream id, nullable, why) for every pattern atom.
    An atom is optional — its captured event may be absent in an emitted
    match — inside an `or` branch, a `count` with min 0, or an absent
    element."""
    if isinstance(el, StreamStateElement):
        sis = el.basic_single_input_stream
        key = sis.stream_reference_id or sis.stream_id
        why = "optional pattern atom: match may emit without it" \
            if optional else None
        kind = "absent pattern stream" \
            if isinstance(el, AbsentStreamStateElement) else why
        yield (key, sis.stream_id,
               optional or isinstance(el, AbsentStreamStateElement),
               kind or "")
    elif isinstance(el, CountStateElement):
        yield from _optional_pattern_refs(
            el.stream_state_element,
            optional or el.min_count == 0)
    elif isinstance(el, LogicalStateElement):
        branch_optional = optional or el.type == "OR"
        yield from _optional_pattern_refs(el.stream_state_element_1,
                                          branch_optional)
        yield from _optional_pattern_refs(el.stream_state_element_2,
                                          branch_optional)
    elif isinstance(el, NextStateElement):
        yield from _optional_pattern_refs(el.state_element, optional)
        yield from _optional_pattern_refs(el.next_state_element, optional)
    elif isinstance(el, EveryStateElement):
        yield from _optional_pattern_refs(el.state_element, optional)


def _build_resolver(app: SiddhiApp, q: Query, kind: str,
                    stream_nulls, inferred_types=None) -> _Resolver:
    r = _Resolver(app, stream_nulls, inferred_types)
    ist = q.input_stream
    if kind == "plain":
        sis = ist
        r.add(sis.stream_reference_id or sis.stream_id, sis.stream_id)
    elif kind == "join":
        jt = ist.type
        for side, sis, nullable_when in (
                ("left", ist.left_input_stream,
                 (JoinInputStream.RIGHT_OUTER_JOIN,
                  JoinInputStream.FULL_OUTER_JOIN)),
                ("right", ist.right_input_stream,
                 (JoinInputStream.LEFT_OUTER_JOIN,
                  JoinInputStream.FULL_OUTER_JOIN))):
            nullable = jt in nullable_when
            r.add(sis.stream_reference_id or sis.stream_id,
                  sis.stream_id, nullable,
                  f"{jt.lower().replace('_', ' ')}: unmatched rows null "
                  f"the {side} side" if nullable else None)
    else:
        for key, sid, nullable, why in _optional_pattern_refs(
                ist.state_element):
            r.add(key, sid, nullable, why or None)
    return r


def _join_sides(q: Query) -> Tuple[set, set]:
    """(left source keys, right source keys) of a join query."""
    ist = q.input_stream
    ls, rs = ist.left_input_stream, ist.right_input_stream
    return ({ls.stream_reference_id or ls.stream_id, ls.stream_id},
            {rs.stream_reference_id or rs.stream_id, rs.stream_id})


def _equi_conjuncts(q: Query, resolver: _Resolver
                    ) -> List[Tuple[object, str, str]]:
    """Top-level `==` conjuncts of a join ON-condition that compare one
    attribute from each side — the fact ROADMAP item 2's equi-join fast
    path (device hash bucketing, IndexEventHolder-style) keys on."""
    on = getattr(q.input_stream, "on_compare", None)
    if on is None:
        return []
    left_keys, right_keys = _join_sides(q)

    def conjuncts(e):
        if isinstance(e, ex.And):
            yield from conjuncts(e.left)
            yield from conjuncts(e.right)
        else:
            yield e

    def side_of(v: ex.Variable) -> Optional[str]:
        if v.stream_id in left_keys:
            return "left"
        if v.stream_id in right_keys:
            return "right"
        return None

    out = []
    for c in conjuncts(on):
        if not isinstance(c, ex.Compare) or c.operator != "==":
            continue
        if not (isinstance(c.left, ex.Variable) and
                isinstance(c.right, ex.Variable)):
            continue
        sl, sr = side_of(c.left), side_of(c.right)
        if sl and sr and sl != sr:
            la, ra = c.left, c.right
            if sl == "right":
                la, ra = ra, la
            out.append((c, f"{la.stream_id}.{la.attribute_name}",
                        f"{ra.stream_id}.{ra.attribute_name}"))
    return out


# ---------------------------------------------------------------------------
# query + app inference
# ---------------------------------------------------------------------------

def _filters_of(q: Query, kind: str):
    """(filter expression, context) pairs across the query's inputs."""
    from ..query_api.query import Filter

    def handlers(sis):
        for h in getattr(sis, "stream_handlers", ()):
            if isinstance(h, Filter):
                yield h.expression

    ist = q.input_stream
    if kind == "plain":
        for e in handlers(ist):
            yield e
    elif kind == "join":
        for sis in (ist.left_input_stream, ist.right_input_stream):
            for e in handlers(sis):
                yield e
    else:
        from ..core.plan_facts import pattern_atoms
        for a in pattern_atoms(ist.state_element):
            for e in handlers(a.basic_single_input_stream):
                yield e


def infer_query(app: SiddhiApp, name: str, q: Query, kind: str,
                stream_nulls, inferred_types=None) -> QueryTypeFlow:
    resolver = _build_resolver(app, q, kind, stream_nulls,
                               inferred_types)
    uses: List[ExprUse] = []

    def record(e, context: str) -> TypeInfo:
        info = infer_expr(e, resolver)
        operands: Tuple[TypeInfo, ...] = ()
        if isinstance(e, (ex.Compare, ex.Add, ex.Subtract, ex.Multiply,
                          ex.Divide, ex.Mod)):
            operands = (infer_expr(e.left, resolver),
                        infer_expr(e.right, resolver))
        uses.append(ExprUse(e, info, context, operands))
        for sub in ex.walk(e):
            if sub is e:
                continue
            if isinstance(sub, (ex.Compare, ex.Add, ex.Subtract,
                                ex.Multiply, ex.Divide, ex.Mod)):
                uses.append(ExprUse(
                    sub, infer_expr(sub, resolver), context,
                    (infer_expr(sub.left, resolver),
                     infer_expr(sub.right, resolver))))
        return info

    for e in _filters_of(q, kind):
        record(e, "filter")
    if kind == "join" and getattr(q.input_stream, "on_compare", None) \
            is not None:
        record(q.input_stream.on_compare, "on")

    outputs: List[Dict] = []
    sel = q.selector
    if sel is not None:
        for a in sel.selection_list or ():
            info = record(a.expression, "select")
            resolver.bound[a.name] = info
            out = {"name": a.name, **info.to_dict()}
            outputs.append(out)
        for g in sel.group_by_list or ():
            record(g, "group_by")
        if sel.having_expression is not None:
            record(sel.having_expression, "having")
    if not outputs and sel is not None and not sel.selection_list:
        # select * : output columns mirror the (first) input source
        for _key, sid, nullable, _why in resolver.sources[:1]:
            d = resolver._definition(sid)
            for a in getattr(d, "attribute_list", ()):
                flow = stream_nulls.get(sid, {}).get(a.name)
                outputs.append({"name": a.name, "type": a.type,
                                "nullable": bool(nullable or
                                                 (flow and
                                                  flow.nullable))})

    flow = QueryTypeFlow(name=name, kind=kind, outputs=outputs,
                         uses=uses)
    if kind == "join":
        flow.equi_conjuncts = _equi_conjuncts(q, resolver)
    return flow


def infer_app(app: SiddhiApp) -> AppTypeFlow:
    """Full-app inference with the inter-query nullability fixpoint:
    a query inserting nullable columns into a stream makes downstream
    readers of that stream see them nullable."""
    from ..core.plan_facts import iter_named_queries, query_kind

    stream_nulls: Dict[str, Dict[str, TypeInfo]] = {}
    inferred_types: Dict[str, Dict[str, str]] = {}
    queries: Dict[str, QueryTypeFlow] = {}
    named = [(name, q, query_kind(q))
             for name, q, _part in iter_named_queries(app)]
    # dataflow fixpoint: nullability/inferred types only ever turn ON,
    # and the lattice is finite (streams × attrs), so this converges in
    # <= |queries|+1 rounds; the bound guards pathological cycles
    for _ in range(len(named) + 1):
        changed = False
        for name, q, kind in named:
            flow = infer_query(app, name, q, kind, stream_nulls,
                               inferred_types)
            queries[name] = flow
            tgt = getattr(q.output_stream, "target_id", None)
            if not tgt:
                continue
            slot = stream_nulls.setdefault(tgt, {})
            tslot = inferred_types.setdefault(tgt, {})
            for col in flow.outputs:
                t = col.get("type")
                if t is not None and tslot.get(col["name"]) != t:
                    tslot[col["name"]] = t
                    changed = True
                if not col.get("nullable"):
                    continue
                prev = slot.get(col["name"])
                if prev is None or not prev.nullable:
                    slot[col["name"]] = TypeInfo(
                        t, True,
                        col.get("why") or f"written nullable by "
                        f"query {name!r}")
                    changed = True
        if not changed:
            break
    return AppTypeFlow(streams=stream_nulls, queries=queries)


def summarize(flow: QueryTypeFlow) -> Dict:
    """JSON-able per-query summary for fingerprints/EXPLAIN: output
    column types + the nullable subset with provenance."""
    return {
        "out_types": [{k: v for k, v in col.items() if k != "why"}
                      for col in flow.outputs],
        "nullable_outputs": [
            {"name": col["name"], "why": col.get("why")}
            for col in flow.outputs if col.get("nullable")],
        "equi_join_keys": [f"{lk} == {rk}"
                           for _, lk, rk in flow.equi_conjuncts],
    }
