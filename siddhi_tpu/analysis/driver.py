"""`analyze()` driver: source text / parsed app / live runtime → findings.

The driver builds one AnalysisContext and runs the rule registry over
it.  It never executes, traces, or compiles anything: the static path
is a pure AST walk; the runtime path reads plan attributes and
shape/dtype metadata (see facts.py).  `tests/test_lint.py` enforces
this by monkeypatching `jax.jit` and `jax.device_get` over a full run.
"""
from __future__ import annotations

from typing import List, Optional, Union

from ..query_api.app import SiddhiApp
from .facts import AnalysisContext, facts_from_app, facts_from_runtime
from .findings import Finding, counts
from .registry import LintConfig, run_rules

# load the built-in rule set into the registry on first import
from . import rules as _rules  # noqa: F401  (import-for-side-effect)


def analyze(target: Union[str, SiddhiApp, object],
            config: Optional[LintConfig] = None,
            source_name: Optional[str] = None) -> List[Finding]:
    """Run every enabled lint rule over `target` and return findings,
    most severe first.

    target: SiddhiQL source text, a parsed SiddhiApp, or a live
    SiddhiAppRuntime.  Source/app analysis derives plan facts
    statically; a runtime contributes its actual compiled-plan facts
    (real emission caps, measured state bytes, mesh-aware fusion
    exclusions) — still without executing or tracing anything.
    """
    config = config or LintConfig()
    runtime = None
    if isinstance(target, str):
        from ..compiler import SiddhiCompiler
        app = SiddhiCompiler.parse(target)
        queries = facts_from_app(app)
    elif isinstance(target, SiddhiApp):
        app = target
        queries = facts_from_app(app)
    elif hasattr(target, "query_runtimes") and hasattr(target, "app"):
        runtime = target
        app = target.app
        queries = facts_from_runtime(target)
    else:
        raise TypeError(
            "analyze() takes SiddhiQL source, a SiddhiApp, or a "
            f"SiddhiAppRuntime, not {type(target).__name__}")
    ctx = AnalysisContext(
        app=app, queries=queries, config=config,
        source_name=source_name or (app.name and f"<{app.name}>")
        or "<app>",
        runtime=runtime)
    return run_rules(ctx, config)


def report(findings: List[Finding]) -> dict:
    """JSON-able report: the REST surface and `--format json` share it."""
    return {
        "findings": [f.to_dict() for f in findings],
        "counts": counts(findings),
    }
