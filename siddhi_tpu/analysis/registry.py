"""Rule registry for the static plan analyzer.

Each rule is registered once with a stable ID, a default severity, and
catalog metadata (title / rationale / fix hint) — `tools/docgen.py`
renders the rule catalog straight from this registry, so docs can never
drift from the shipped rule set.  Per-run enable/severity overrides ride
a LintConfig instead of mutating the registry.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Iterable, List, Optional, Set

from .findings import SEVERITIES, Finding


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    severity: str            # default severity (INFO | WARN | ERROR)
    title: str
    rationale: str           # why this is a TPU/production hazard
    hint: str                # generic fix hint (findings may specialize)
    check: Callable          # (ctx) -> Iterable[Finding]


RULES: Dict[str, Rule] = {}


def rule(id: str, severity: str, title: str, rationale: str, hint: str):
    """Decorator registering a check function as a lint rule.  The check
    receives an AnalysisContext and yields Findings; the driver stamps
    rule id / severity (with config overrides) onto whatever it yields."""
    if severity not in SEVERITIES:
        raise ValueError(f"bad severity {severity!r} for rule {id!r}")

    def deco(fn: Callable) -> Callable:
        if id in RULES:
            raise ValueError(f"duplicate rule id {id!r}")
        RULES[id] = Rule(id=id, severity=severity, title=title,
                         rationale=rationale, hint=hint, check=fn)
        return fn
    return deco


@dataclasses.dataclass
class LintConfig:
    """Per-run analyzer configuration.

    disabled: rule IDs to skip entirely.
    severity_overrides: {rule_id: severity} — e.g. promote MEM001 to
        ERROR in CI, demote DEAD002 to INFO on legacy apps.
    state_budget_bytes: MEM001 threshold — estimated per-query device
        state above this fires (default 128 MiB: a few queries of that
        size exhaust a 16 GB HBM chip once batches/emissions join them).
    mesh_devices: PART002 deploy target — the shard-mesh size the app
        will serve on (0 = unknown; runtime analysis resolves it from
        the live runtime's mesh instead).
    global_state_ceiling_bytes: ADM001 deploy target — the box's
        `admission.global.max.state.bytes` admission ceiling (0 =
        unknown; runtime analysis resolves it from the live manager's
        config instead).
    """

    disabled: Set[str] = dataclasses.field(default_factory=set)
    severity_overrides: Dict[str, str] = \
        dataclasses.field(default_factory=dict)
    state_budget_bytes: int = 128 * 1024 * 1024
    mesh_devices: int = 0
    global_state_ceiling_bytes: int = 0

    def severity_of(self, r: Rule) -> str:
        return self.severity_overrides.get(r.id, r.severity)

    def enabled_rules(self) -> List[Rule]:
        return [RULES[k] for k in sorted(RULES) if k not in self.disabled]


def catalog() -> List[Dict]:
    """Stable-ordered rule catalog for docgen and `lint --rules`."""
    return [
        {"id": r.id, "severity": r.severity, "title": r.title,
         "rationale": r.rationale, "hint": r.hint}
        for _, r in sorted(RULES.items())
    ]


def run_rules(ctx, config: Optional[LintConfig] = None) -> List[Finding]:
    """Run every enabled rule over one AnalysisContext and return the
    stamped, deterministically-sorted findings."""
    config = config or LintConfig()
    out: List[Finding] = []
    for r in config.enabled_rules():
        sev = config.severity_of(r)
        produced: Iterable[Finding] = r.check(ctx) or ()
        for f in produced:
            f.rule_id = r.id
            # a rule may grade its own findings (JOIN002: fast path
            # ACTIVE = INFO, inapplicable = WARN); an explicit config
            # override still forces every finding of the rule
            if not f.severity or r.id in config.severity_overrides:
                f.severity = sev
            if f.source is None:
                f.source = ctx.source_name
            if f.hint is None:
                f.hint = r.hint
            out.append(f)
    out.sort(key=lambda f: f.sort_key())
    return out
