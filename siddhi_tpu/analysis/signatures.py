"""Canonical step signatures: argument ShapeDtypeStructs WITHOUT traffic.

`steputil.jit_step` captures the argument avals of the last real trace,
and EXPLAIN re-lowers from them — which means cost analysis is only
available after a query has served traffic.  The plan auditor
(analysis/audit.py) must grade a compiled plan in CI *before* anything
runs, so this module synthesizes the same ShapeDtypeStructs from plan
metadata alone: state leaves come from the runtime's allocated state
pytree (shape/dtype reads, never fetched), batch axes from the plan's
capacities, and scalar/now/selection columns from the exact layouts the
runtime paths build (`core/runtime.py` process_staged variants — each
synthesizer cites its path).

The synthesized signature is CANONICAL, not "whatever the last batch
happened to be": full batch of `batch_capacity` rows, and for keyed/NFA
layouts a deterministic grouping of G = min(key_capacity, B) key rows
of E = B // G events each.  Canonical signatures make fingerprints
comparable across commits — the auditor diffs like against like — and
`tests/test_audit.py` asserts the synthesized plain-step signature is
byte-identical to the signature real traffic traces.

Everything here is metadata arithmetic: no jax dispatch, no transfer,
no trace (lowering happens in the consumer, under RECOMPILES.suppress).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np


def _sds(shape, dtype):
    import jax
    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


def _tree_specs(tree):
    """ShapeDtypeStruct twin of an allocated state pytree (metadata
    reads only)."""
    import jax
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(tuple(x.shape), np.dtype(x.dtype)),
        tree)


def _table_specs(app, deps) -> Tuple:
    """Spec twin of `SiddhiAppRuntime.in_probe_tables` snapshots:
    (first column, validity) per dep."""
    out = []
    for d in deps or ():
        t = app.tables[d]
        out.append((_sds(t.cols[0].shape, t.cols[0].dtype),
                    _sds(t.valid.shape, t.valid.dtype)))
    return tuple(out)


def _canonical_grouping(key_capacity: int, B: int) -> Tuple[int, int]:
    """Deterministic [G, E] key grouping for keyed/NFA layouts: G keys
    of E events each covering one full batch (G=1 ⇒ the single-key
    steady state non-partitioned patterns run)."""
    G = max(1, min(int(key_capacity or 1), B))
    E = max(1, B // G)
    return G, E


# np staging dtypes (event.np_dtype) — pattern steps receive the raw
# host staging columns; plain/join steps receive device-schema columns
def _staging_cols(schema, B: int) -> Tuple:
    from ..core import event as ev
    return tuple(_sds((B,), ev.np_dtype(t)) for t in schema.types)


def _device_cols(schema, B: int) -> Tuple:
    return tuple(_sds((B,), d) for d in schema.dtypes)


# ---------------------------------------------------------------------------
# per-kind synthesizers
# ---------------------------------------------------------------------------

def _plain_specs(qr) -> Dict[str, Tuple]:
    """QueryRuntime.process_staged / _process_keyed argument layouts."""
    p = qr.planned
    B = int(p.batch_capacity)
    state = _tree_specs(qr.state)
    ts = _sds((B,), np.int64)
    kind = _sds((B,), np.int32)
    valid = _sds((B,), np.bool_)
    cols = _device_cols(p.in_schema, B)
    gslot = _sds((B,), np.int32)
    now = _sds((), np.int64)
    in_tabs = _table_specs(qr.app, p.in_deps)
    if p.keyed_window:
        G, E = _canonical_grouping(p.key_capacity, B)
        key_idx = _sds((G,), np.int32)
        sel = _sds((G, E), np.int32)
        return {"step": (state, ts, kind, valid, cols, gslot, key_idx,
                         sel, now, in_tabs)}
    pslots = tuple(_sds((B,), np.int32) for _ in p.pair_allocs)
    return {"step": (state, ts, kind, valid, cols, gslot, now, in_tabs,
                     pslots)}


def _pattern_specs(qr) -> Dict[str, Tuple]:
    """PatternQueryRuntime.process_staged argument layouts, one entry
    per compiled step variant (plain / ts-delta wire / dense slice /
    sharded / timer)."""
    from ..core.plan_facts import BATCH_CAPACITY
    p = qr.planned
    B = BATCH_CAPACITY
    pstate, sel_state = (_tree_specs(qr.state[0]),
                         _tree_specs(qr.state[1]))
    now = _sds((), np.int64)
    in_tabs = _table_specs(qr.app, getattr(p.exec, "in_deps", None) or ())
    sharded = getattr(p, "mesh", None) is not None
    if p.partition_positions or sharded:
        G, E = _canonical_grouping(p.key_capacity, B)
    else:
        G, E = 1, B
    key_idx = _sds((G,), np.int32)
    sel = _sds((G, E), np.int32)
    out: Dict[str, Tuple] = {}
    for sid in p.spec.stream_ids:
        schema = p.in_schemas[sid]
        raw_cols = _staging_cols(schema, B)
        raw_ts = _sds((B,), np.int64)
        out[f"step[{sid}]"] = (pstate, sel_state, raw_cols, raw_ts,
                               sel, key_idx, now, in_tabs)
        if p.steps_w is not None and sid in p.steps_w:
            # ts-delta wire twin: (base scalar i64, delta i32 column)
            out[f"step_w[{sid}]"] = (
                pstate, sel_state, raw_cols, _sds((), np.int64),
                _sds((B,), np.int32), sel, key_idx, now, in_tabs)
        if p.dense_steps is not None and sid in p.dense_steps:
            # contiguous-slot fast path takes a scalar key_lo
            out[f"dense_step[{sid}]"] = (
                pstate, sel_state, raw_cols, raw_ts, sel,
                _sds((), np.int32), now, in_tabs)
        if p.dense_steps_w is not None and sid in p.dense_steps_w:
            out[f"dense_step_w[{sid}]"] = (
                pstate, sel_state, raw_cols, _sds((), np.int64),
                _sds((B,), np.int32), sel, _sds((), np.int32), now,
                in_tabs)
    if p.timer_step is not None:
        out["timer_step"] = (pstate, sel_state, now, in_tabs)
    return out


def _join_side_other(qr, is_left: bool) -> Optional[Tuple]:
    """Spec twin of JoinQueryRuntime._other_table: live table / named
    window buffer metadata, or the (1,)-dummy for stream sides."""
    p = qr.planned
    other = p.right if is_left else p.left
    app = qr.app
    if getattr(other, "is_aggregation", False):
        return None                 # aggregation view: duration-dependent
    if getattr(other, "is_named_window", False):
        nw = app.named_windows[other.stream_id]
        buf = nw.wproc.current_buffer(nw.state)
        return (tuple(_sds(c.shape, c.dtype) for c in buf.cols),
                _sds(buf.ts.shape, buf.ts.dtype),
                _sds(buf.alive.shape, buf.alive.dtype))
    if getattr(other, "is_table", False):
        t = app.tables[other.stream_id]
        return (tuple(_sds(c.shape, c.dtype) for c in t.cols),
                _sds(t.ts.shape, t.ts.dtype),
                _sds(t.valid.shape, t.valid.dtype))
    f1 = _sds((1,), np.float32)     # jnp.zeros((1,)) default dtype is f32
    return (f1, f1, f1)


def _join_specs(qr) -> Dict[str, Tuple]:
    """JoinQueryRuntime.process_staged argument layout per side."""
    p = qr.planned
    B = int(p.batch_capacity)
    state = _tree_specs(qr.state)
    now = _sds((), np.int64)
    out: Dict[str, Tuple] = {}
    for role, is_left, side, step in (("step[left]", True, p.left,
                                       p.step_left),
                                      ("step[right]", False, p.right,
                                       p.step_right)):
        if step is None:
            continue
        other = _join_side_other(qr, is_left)
        if other is None:
            continue
        args = [state, _sds((B,), np.int64), _sds((B,), np.int32),
                _sds((B,), np.bool_), _device_cols(side.schema, B),
                _sds((B,), np.int32)]
        # equi-join fast-path probe arg (core/join.py): bucket slots or
        # host table candidates ride between gslot and the other-side
        # snapshot
        if getattr(p, "fastpath", None) == "bucket":
            args.append(_sds((B,), np.int32))
        elif getattr(p, "fastpath", None) == "table":
            tid = (p.left if p.table_is_left else p.right).stream_id
            t = qr.app.tables[tid]
            w = (t.indexes[p.table_pos].lanes.shape[1]
                 if p.table_pos in t.indexes else 1)
            args.append((_sds((B, w), np.int32), _sds((B, w), np.bool_)))
        args += [other, now]
        out[role] = tuple(args)
    return out


def synthesize(qr, kind: str) -> Dict[str, Tuple]:
    """{step role: argspec pytree} for every compiled step variant of a
    query runtime the auditor can grade statically.  Roles match
    `observability.explain._steps_of` naming so fingerprints, EXPLAIN
    and recompile owners line up.  Unsupported variants are simply
    absent (the auditor reports them unavailable, never guesses)."""
    try:
        if kind == "pattern":
            return _pattern_specs(qr)
        if kind == "join":
            return _join_specs(qr)
        return _plain_specs(qr)
    except Exception:  # noqa: BLE001 — diagnostics must not throw
        return {}


def spec_for_role(qr, kind: str, role: str) -> Optional[Tuple]:
    return synthesize(qr, kind).get(role)


def primary_roles(qr, kind: str) -> List[str]:
    """The steady-state hot-path program per batch: what ONE dispatch
    of real traffic runs (ts-delta wire twin when it exists — that is
    what steady traffic traces), summed across pattern streams / join
    sides by the auditor's totals."""
    p = qr.planned
    if kind == "pattern":
        roles = []
        for sid in p.spec.stream_ids:
            if p.steps_w is not None and sid in p.steps_w:
                roles.append(f"step_w[{sid}]")
            else:
                roles.append(f"step[{sid}]")
        return roles
    if kind == "join":
        return [r for r, s in (("step[left]", p.step_left),
                               ("step[right]", p.step_right))
                if s is not None]
    return ["step"]


def step_for_role(qr, kind: str, role: str) -> Optional[Any]:
    """The jitted fn a role names (same mapping _steps_of renders)."""
    p = qr.planned
    if role == "step" and kind not in ("pattern",):
        return getattr(p, "step", None)
    if role == "timer_step":
        return getattr(p, "timer_step", None)
    if role == "step[left]":
        return getattr(p, "step_left", None)
    if role == "step[right]":
        return getattr(p, "step_right", None)
    if "[" in role and role.endswith("]"):
        base, sid = role[:-1].split("[", 1)
        d = {"step": getattr(p, "steps", None),
             "step_w": getattr(p, "steps_w", None),
             "dense_step": getattr(p, "dense_steps", None),
             "dense_step_w": getattr(p, "dense_steps_w", None),
             "shard_fused_step": getattr(p, "shard_fused_steps", None),
             }.get(base)
        if isinstance(d, dict):
            return d.get(sid)
    return None
