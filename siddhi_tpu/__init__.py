"""siddhi_tpu — a TPU-native streaming SQL / complex event processing framework.

Brand-new implementation of the capability surface of Siddhi 5.x
(https://github.com/siddhi-io/siddhi; mounted read-only at /root/reference)
re-architected for JAX/XLA: queries compile to pure functions over columnar
event micro-batches `(state, batch) -> (state', outputs)`, partition keys
shard across the TPU mesh, group-by aggregates run as segmented scans, and
pattern NFAs advance as vectorized transitions.  See SURVEY.md.
"""
import os

# XLA:CPU's new fusion emitters (jaxlib 0.9.0) miscompile some of our jitted
# pattern steps (LLVM IR verifier failure in fusion_compiler.cc — e.g. a
# 2-column (long,int) partitioned NFA step) and compile slower than the
# legacy emitters.  Best-effort opt-out before the backend initializes; a
# no-op for TPU and for processes that already compiled something.
# VERSION-GATED: older jaxlibs (< 0.9) don't know the flag, and XLA
# hard-aborts the process on unknown XLA_FLAGS — the opt-out must only be
# injected where the flag exists.
def _jaxlib_has_fusion_emitters() -> bool:
    try:
        import jaxlib
        major, minor = (int(x) for x in jaxlib.__version__.split(".")[:2])
        return (major, minor) >= (0, 9)
    except Exception:  # noqa: BLE001 — never block import on a probe
        return False


if "--xla_cpu_use_fusion_emitters" not in os.environ.get("XLA_FLAGS", "") \
        and _jaxlib_has_fusion_emitters():
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_cpu_use_fusion_emitters=false")

import jax                                                       # noqa: E402

# LONG attributes and epoch-ms timestamps need 64-bit ints (i32 overflows in
# 2038 and on any epoch-ms value); XLA:TPU emulates s64.  DOUBLE still maps
# to f32 on device (core/event.py) since TPUs have no f64.
jax.config.update("jax_enable_x64", True)

from .core.event import Event                                    # noqa: E402
from .core.runtime import (                                      # noqa: E402
    InputHandler,
    QueryCallback,
    SiddhiAppRuntime,
    SiddhiManager,
    StreamCallback,
)
from . import query_api                                          # noqa: E402

__version__ = "0.1.0"
__all__ = [
    "Event", "InputHandler", "QueryCallback", "SiddhiAppRuntime",
    "SiddhiManager", "StreamCallback", "query_api",
]
