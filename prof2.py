import time, sys
import numpy as np
import jax

N_KEYS = 1 << 20
BATCH = 1 << 17
QL = f"""
@app:playback
@async
define stream TradeStream (key long, price float, volume int);
partition with (key of TradeStream)
begin
  @capacity(keys='{N_KEYS}', slots='4')
  @emit(rows='2')
  @info(name='flagship')
  from every e1=TradeStream[volume == 1]
       -> e2=TradeStream[volume == 2 and price >= e1.price]
       -> e3=TradeStream[volume == 3]
       -> e4=TradeStream[volume == 4 and price >= e3.price]
  select e1.key as k, e1.price as p1, e2.price as p2, e4.price as p4
  insert into Matches;
end;
"""
from siddhi_tpu import SiddhiManager
from siddhi_tpu.core import event as ev
from siddhi_tpu.core.keyslots import group_events_by_key

manager = SiddhiManager()
rt = manager.create_siddhi_app_runtime(QL)
matches = [0]
rt.add_batch_callback("flagship", lambda ts, b: matches.__setitem__(0, matches[0] + b["n_current"]))
rt.start()
h = rt.get_input_handler("TradeStream")
blocks = N_KEYS // BATCH
key_block = {b: np.repeat(np.arange(b * BATCH, (b + 1) * BATCH, dtype=np.int64), 4) for b in range(blocks)}
vol4 = np.tile(np.array([1, 2, 3, 4], np.int32), BATCH)
price4 = vol4.astype(np.float32)
clock = [1000]
def send(block):
    clock[0] += 10
    ts = clock[0] + np.tile(np.arange(4, dtype=np.int64), BATCH)
    h.send_columns([key_block[block], price4, vol4], timestamps=ts)
for b in range(blocks):
    send(b)       # warm all keys + compile
rt.flush()
print("warm done", file=sys.stderr)

# instrument pieces
qr = rt.query_runtimes["flagship"]
p = qr.planned
pos = p.partition_positions["TradeStream"]
block = 3
n = 4 * BATCH
cap = ev.bucket_size(n)
schema = rt.junctions["TradeStream"].schema
cols = [key_block[block], price4, vol4]
for it in range(3):
    t0 = time.perf_counter()
    ts = np.zeros((cap,), np.int64); ts[:n] = clock[0]
    valid = np.zeros((cap,), np.bool_); valid[:n] = True
    padded = []
    for c, t in zip(cols, schema.types):
        a = np.zeros((cap,), ev.np_dtype(t)); a[:n] = c
        padded.append(a)
    t1 = time.perf_counter()
    slots = qr.slot_allocator.slots_for([padded[i] for i in pos], valid)
    t2 = time.perf_counter()
    key_idx_np, sel, _ = group_events_by_key(slots, valid, pad=p.key_capacity)
    t3 = time.perf_counter()
    raw_cols = tuple(jax.numpy.asarray(c) for c in padded)
    raw_ts = jax.numpy.asarray(ts)
    sel_d = jax.numpy.asarray(sel)
    t4 = time.perf_counter()
    pstate, sel_state = qr.state
    out = p.dense_steps["TradeStream"](
        pstate, sel_state, raw_cols, raw_ts, sel_d,
        jax.numpy.asarray(int(key_idx_np[0]), jax.numpy.int32),
        jax.numpy.asarray(clock[0], jax.numpy.int64))
    t5 = time.perf_counter()
    qr.state = (out[0], out[1])
    jax.block_until_ready(out[0])
    t6 = time.perf_counter()
    print(f"pad={1000*(t1-t0):.1f} slots={1000*(t2-t1):.1f} group={1000*(t3-t2):.1f} "
          f"h2d-dispatch={1000*(t4-t3):.1f} step-dispatch={1000*(t5-t4):.1f} "
          f"block={1000*(t6-t5):.1f} total={1000*(t6-t0):.1f}ms", file=sys.stderr)

# end-to-end send timing, steady state
lat = []
for sweep in range(2):
    for b in range(blocks):
        ta = time.perf_counter()
        send(b)
        lat.append(time.perf_counter() - ta)
rt.flush()
lat = np.array(sorted(lat)) * 1000
print(f"send p50={lat[len(lat)//2]:.1f}ms p90={lat[int(len(lat)*0.9)]:.1f}ms max={lat[-1]:.1f}ms", file=sys.stderr)
manager.shutdown()
